"""batonlint engine — file walking, suppressions, registry, reporters.

Deliberately dependency-free (stdlib ``ast`` only): the lint step must
run in CI before any heavyweight install, and importing this module
must never drag in jax/aiohttp. Checkers register themselves through
:func:`register`; :mod:`baton_tpu.analysis.checkers` imports the five
rule modules for their registration side effect.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Checker",
    "CheckContext",
    "Finding",
    "ProjectChecker",
    "Report",
    "all_rules",
    "apply_baseline",
    "finding_fingerprints",
    "register",
    "run_paths",
    "run_project_sources",
    "run_source",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``also_lines`` are additional lines where a ``# batonlint:
    allow[RULE]`` comment suppresses this finding — e.g. a BTL002
    await-under-lock finding is suppressible at the ``async with
    <lock>:`` header as well as at the await itself, so one comment
    covers a whole deliberately-held lock block.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    also_lines: Tuple[int, ...] = ()

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclasses.dataclass
class Report:
    """Aggregate result of one lint run."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: int = 0
    files_checked: int = 0
    errors: List[str] = dataclasses.field(default_factory=list)
    # incremental summary cache stats (run_paths with cache_path only):
    # hits = files whose per-function facts were reused by content hash
    cache_hits: int = 0
    cache_misses: int = 0
    # findings absorbed by --baseline (still real; just pre-existing)
    baselined: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


_ALLOW_RE = re.compile(r"#\s*batonlint:\s*allow\[([^\]]*)\]")


def _comment_lines(source: str):
    """``(lineno, comment_text)`` for every REAL comment token.

    Tokenizing (rather than regex over raw lines) keeps ``allow[...]``
    text inside docstrings and string literals — rule documentation,
    fixture sources embedded in tests — from acting as (and being
    audited as) live suppressions.  Sources that fail to tokenize fall
    back to the raw-line scan so a stray ``\\x0c`` can't disable
    suppressions wholesale."""
    import io
    import tokenize

    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return [
            (lineno, text)
            for lineno, text in enumerate(source.splitlines(), start=1)
            if "#" in text
        ]
    return [
        (tok.start[0], tok.string)
        for tok in tokens
        if tok.type == tokenize.COMMENT
    ]


class Suppressions:
    """Per-line ``# batonlint: allow[RULE1,RULE2]`` / ``allow[*]`` map.

    Each suppression that actually absorbs a finding is recorded in
    ``used`` (``line -> {rules it silenced}``) so the BTL000 audit can
    flag allow comments that no longer silence anything."""

    def __init__(self, source: str) -> None:
        self._by_line: Dict[int, frozenset] = {}
        self.used: Dict[int, set] = {}
        for lineno, text in _comment_lines(source):
            m = _ALLOW_RE.search(text)
            if m:
                rules = frozenset(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                self._by_line[lineno] = rules

    def allows(self, line: int, rule: str) -> bool:
        rules = self._by_line.get(line)
        if rules is None:
            return False
        if rule == "BTL000":
            # the stale-suppression audit may only be silenced by an
            # EXPLICIT allow[BTL000]: otherwise a stale `allow[*]`
            # would absorb its own staleness finding and never surface
            return rule in rules
        return rule in rules or "*" in rules

    def match(self, finding: Finding) -> Optional[int]:
        """First line whose allow comment covers the finding, else
        None.  Marks that line as used for the finding's rule."""
        for line in (finding.line, *finding.also_lines):
            if self.allows(line, finding.rule):
                self.used.setdefault(line, set()).add(finding.rule)
                return line
        return None

    def allows_finding(self, finding: Finding) -> bool:
        return self.match(finding) is not None

    def entries(self):
        """``(line, frozenset_of_rule_tokens)`` pairs, source order."""
        return sorted(self._by_line.items())


def _normalize_registry(reg) -> Optional[dict]:
    """Metric-registry normalization for BTL030.

    Accepts the legacy 2-tuple ``(declared_counters, counter_prefixes)``
    (timer/gauge audit disabled — pre-existing fixtures keep passing)
    or the full dict shape with ``counters`` / ``counter_prefixes`` /
    ``timers`` / ``gauges`` / ``exemplar_timers`` keys, where
    ``timers``/``gauges``/``exemplar_timers`` may be None to disable
    that audit."""
    if reg is None:
        return None
    if isinstance(reg, dict):
        return {
            "counters": frozenset(reg.get("counters", ())),
            "counter_prefixes": tuple(reg.get("counter_prefixes", ())),
            "timers": (
                frozenset(reg["timers"])
                if reg.get("timers") is not None else None
            ),
            "gauges": (
                frozenset(reg["gauges"])
                if reg.get("gauges") is not None else None
            ),
            "exemplar_timers": (
                frozenset(reg["exemplar_timers"])
                if reg.get("exemplar_timers") is not None else None
            ),
        }
    names, prefixes = reg
    return {
        "counters": frozenset(names),
        "counter_prefixes": tuple(prefixes),
        "timers": None,
        "gauges": None,
        "exemplar_timers": None,
    }


class CheckContext:
    """Everything a checker may need about the file under analysis."""

    def __init__(
        self,
        path: str,
        source: str,
        tree: ast.Module,
        counter_registry=None,
    ) -> None:
        self.path = path
        self.posix_path = pathlib.PurePath(path).as_posix()
        self.parts = pathlib.PurePath(path).parts
        self.source = source
        self.tree = tree
        # BTL030: normalized metric registry dict (counters / prefixes /
        # timers / gauges), resolved by the runner from
        # baton_tpu/utils/metrics.py or injected by tests (legacy
        # 2-tuple accepted).
        self.counter_registry = _normalize_registry(counter_registry)


class Checker:
    """Base class: subclasses set ``rule``/``title`` and implement
    :meth:`check`; :meth:`applies_to` scopes the rule by path."""

    rule: str = ""
    title: str = ""

    def applies_to(self, ctx: CheckContext) -> bool:
        return True

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        raise NotImplementedError


class ProjectChecker(Checker):
    """Whole-program checker: runs once per lint run against the
    :class:`~baton_tpu.analysis.project.Project` (every parsed file)
    instead of once per file, and may emit findings in any of them.
    Per-line suppressions still apply — each finding is matched against
    the suppression map of the file it points into."""

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        return ()  # project checkers never run in the per-file pass

    def check_project(self, project) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Checker] = {}


def register(checker_cls):
    """Class decorator: instantiate and register a checker by rule id."""
    inst = checker_cls()
    if not inst.rule:
        raise ValueError(f"{checker_cls.__name__} has no rule id")
    if inst.rule in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.rule}")
    _REGISTRY[inst.rule] = inst
    return checker_cls


def all_rules() -> Dict[str, str]:
    """``{rule_id: one-line title}`` for every registered checker."""
    _load_checkers()
    return {rule: _REGISTRY[rule].title for rule in sorted(_REGISTRY)}


def _load_checkers() -> None:
    # import for the registration side effect; idempotent
    from baton_tpu.analysis import checkers  # noqa: F401


def _select(rules: Optional[Sequence[str]]) -> List[Checker]:
    _load_checkers()
    if rules is None:
        return [_REGISTRY[r] for r in sorted(_REGISTRY)]
    unknown = sorted(set(rules) - set(_REGISTRY))
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    return [_REGISTRY[r] for r in sorted(set(rules))]


def _run_project(
    project,
    rules: Optional[Sequence[str]],
    report: Report,
    only_paths: Optional[frozenset] = None,
) -> List[Finding]:
    """Shared core: per-file checkers over each module, then project
    checkers once over the whole :class:`Project`.  ``only_paths``
    (already-normalized path strings) restricts which files run the
    per-file pass and which files' findings are REPORTED — project
    checkers still see every module, so cross-module reasoning stays
    sound under ``--changed-only``."""
    checkers = _select(rules)
    suppressions = {m.path: Suppressions(m.source) for m in project.modules}
    findings: List[Finding] = []
    seen = set()
    crashed: set = set()

    def wanted(path: str) -> bool:
        return only_paths is None or _normalize_path(path) in only_paths

    def admit(f: Finding) -> None:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key in seen:
            return
        seen.add(key)
        # match suppressions BEFORE the --changed-only filter: usage
        # marks must be complete for the BTL000 stale-suppression audit
        # even when the finding's file isn't being reported on
        supp = suppressions.get(f.path)
        suppressed = supp is not None and supp.allows_finding(f)
        if not wanted(f.path):
            return
        if suppressed:
            report.suppressed += 1
        else:
            findings.append(f)

    for mod in project.modules:
        report.files_checked += 1
        if not wanted(mod.path):
            continue
        ctx = CheckContext(
            mod.path, mod.source, mod.tree,
            counter_registry=mod.counter_registry,
        )
        for checker in checkers:
            if isinstance(checker, ProjectChecker):
                continue
            if not checker.applies_to(ctx):
                continue
            try:
                raw = list(checker.check(ctx))
            except Exception as exc:  # a buggy checker must not kill the run
                report.errors.append(
                    f"{mod.path}: checker {checker.rule} crashed: {exc!r}"
                )
                crashed.add(checker.rule)
                continue
            for f in raw:
                admit(f)
    for checker in checkers:
        if not isinstance(checker, ProjectChecker):
            continue
        try:
            raw = list(checker.check_project(project))
        except Exception as exc:
            report.errors.append(
                f"checker {checker.rule} crashed: {exc!r}"
            )
            crashed.add(checker.rule)
            continue
        for f in raw:
            admit(f)
    if any(c.rule == "BTL000" for c in checkers):
        for f in _audit_suppressions(
            project, checkers, suppressions, crashed, wanted, only_paths
        ):
            admit(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report.findings.extend(findings)
    return findings


def _audit_suppressions(
    project, checkers, suppressions, crashed, wanted, only_paths
) -> List[Finding]:
    """BTL000 — an ``allow[RULE]`` that silences nothing is itself a
    finding: it documents a hazard that no longer exists (or never
    did), and it will hide the next REAL instance introduced on that
    line.  Runs after every other checker so usage marks are complete.

    A named token is audited only when its rule actually ran this pass
    without crashing; ``*`` tokens are stale when the line silenced
    nothing at all.  Under ``--changed-only`` the per-file pass skips
    unchanged files, so only files in the filter are audited."""
    ran = {c.rule for c in checkers if c.rule != "BTL000"} - crashed
    out: List[Finding] = []
    for mod in project.modules:
        # files outside --changed-only never ran the per-file pass, so
        # their per-file-rule suppressions would all look stale
        if only_paths is not None and not wanted(mod.path):
            continue
        supp = suppressions.get(mod.path)
        if supp is None:
            continue
        for line, tokens in supp.entries():
            used = supp.used.get(line, set())
            for tok in sorted(tokens):
                if tok == "*":
                    if not used:
                        out.append(Finding(
                            "BTL000", mod.path, line, 0,
                            "stale suppression: `allow[*]` silences "
                            "nothing on this line; remove it",
                        ))
                elif tok in ran and tok not in used:
                    out.append(Finding(
                        "BTL000", mod.path, line, 0,
                        f"stale suppression: `allow[{tok}]` but {tok} "
                        f"no longer fires here; remove it (stale "
                        f"allows hide the next real instance)",
                    ))
    return out


def _normalize_path(path: str) -> str:
    try:
        return str(pathlib.Path(path).resolve())
    except OSError:
        return path


def _parse_entries(
    items, report: Report
) -> list:
    """``(path, source[, registry])`` -> parsed Project entries; syntax
    errors land on the report, mirroring the old per-file behavior."""
    entries = []
    for item in items:
        path, source = item[0], item[1]
        registry = item[2] if len(item) > 2 else None
        try:
            tree = ast.parse(source)
        except SyntaxError as exc:
            report.errors.append(
                f"{path}:{exc.lineno}: syntax error: {exc.msg}"
            )
            continue
        entries.append((path, source, tree, registry))
    return entries


def _build_project(entries):
    from baton_tpu.analysis.project import Project

    return Project.from_parsed(entries)


def run_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
    counter_registry=None,
    report: Optional[Report] = None,
) -> List[Finding]:
    """Lint one source string (the unit-test entry point).

    ``path`` scopes path-sensitive rules (BTL001/BTL030 only fire under
    a ``server/`` directory), so fixtures pass paths like
    ``"baton_tpu/server/x.py"``. Project-scoped rules see a one-module
    project. Returns unsuppressed findings sorted by location;
    suppressed counts land on ``report`` when given.
    """
    report = report if report is not None else Report()
    entries = _parse_entries([(path, source, counter_registry)], report)
    if not entries:
        return []
    return _run_project(_build_project(entries), rules, report)


def run_project_sources(
    files,
    rules: Optional[Sequence[str]] = None,
    report: Optional[Report] = None,
) -> List[Finding]:
    """Lint several in-memory modules as ONE project — the multi-module
    fixture entry point (cross-module lock order, import resolution).
    ``files`` is ``{path: source}`` or an iterable of ``(path, source)``;
    module names derive from the paths (``fixtures/liba.py`` imports as
    ``fixtures.liba``)."""
    report = report if report is not None else Report()
    items = files.items() if hasattr(files, "items") else list(files)
    entries = _parse_entries(list(items), report)
    return _run_project(_build_project(entries), rules, report)


def iter_python_files(paths: Sequence[str]) -> List[pathlib.Path]:
    """Expand files/directories into a sorted, deduplicated .py list."""
    out = []
    seen = set()
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            candidates = sorted(path.rglob("*.py"))
        else:
            candidates = [path]
        for c in candidates:
            if "__pycache__" in c.parts:
                continue
            key = str(c)
            if key not in seen:
                seen.add(key)
                out.append(c)
    return out


def _resolve_counter_registry(
    path: pathlib.Path,
    cache: Dict[str, Optional[dict]],
) -> Optional[dict]:
    """Find the package's declared-metric registry for a checked file.

    Walks the file's ancestors for a ``baton_tpu/utils/metrics.py``
    (covering both in-repo paths and fixture trees) and parses its
    ``DECLARED_COUNTERS`` / ``DECLARED_COUNTER_PREFIXES`` /
    ``DECLARED_TIMERS`` / ``DECLARED_GAUGES`` /
    ``DECLARED_EXEMPLAR_TIMERS`` literals with
    ``ast.literal_eval`` — no import, so linting never executes package
    code. ``None`` (registry not found) disables BTL030 for the file;
    a registry without timer/gauge sets disables just those audits.
    """
    for ancestor in [path.parent, *path.parent.parents]:
        for candidate in (
            ancestor / "baton_tpu" / "utils" / "metrics.py",
            ancestor / "utils" / "metrics.py",
        ):
            key = str(candidate)
            if key in cache:
                if cache[key] is not None:
                    return cache[key]
                continue
            if not candidate.is_file():
                cache[key] = None
                continue
            cache[key] = _parse_counter_registry(candidate)
            if cache[key] is not None:
                return cache[key]
    return None


def _parse_counter_registry(
    metrics_path: pathlib.Path,
) -> Optional[dict]:
    try:
        tree = ast.parse(metrics_path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return None
    names: Optional[frozenset] = None
    prefixes: tuple = ()
    timers: Optional[frozenset] = None
    gauges: Optional[frozenset] = None
    exemplar_timers: Optional[frozenset] = None
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = node.value
        # unwrap frozenset({...}) / tuple([...]) wrapper calls
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("frozenset", "tuple", "set")
            and len(value.args) == 1
        ):
            value = value.args[0]
        try:
            literal = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            continue
        if target.id == "DECLARED_COUNTERS":
            names = frozenset(str(x) for x in literal)
        elif target.id == "DECLARED_COUNTER_PREFIXES":
            prefixes = tuple(str(x) for x in literal)
        elif target.id == "DECLARED_TIMERS":
            timers = frozenset(str(x) for x in literal)
        elif target.id == "DECLARED_GAUGES":
            gauges = frozenset(str(x) for x in literal)
        elif target.id == "DECLARED_EXEMPLAR_TIMERS":
            exemplar_timers = frozenset(str(x) for x in literal)
    if names is None:
        return None
    return {
        "counters": names,
        "counter_prefixes": prefixes,
        "timers": timers,
        "gauges": gauges,
        "exemplar_timers": exemplar_timers,
    }


# -- finding fingerprints / baseline -----------------------------------
def _fingerprint_base(f: Finding) -> str:
    """Location-independent identity of a finding: rule + posix path +
    the message with every digit run collapsed — stable across pure
    line-number drift (the property SARIF ``partialFingerprints`` and
    ``--baseline`` need), while a finding MOVING to another file or
    changing meaning gets a new identity."""
    import hashlib

    norm_msg = re.sub(r"\d+", "#", f.message)
    posix = pathlib.PurePath(f.path).as_posix()
    return hashlib.sha256(
        f"{f.rule}\x00{posix}\x00{norm_msg}".encode("utf-8")
    ).hexdigest()[:16]


def finding_fingerprints(findings: Sequence[Finding]) -> List[str]:
    """One stable fingerprint per finding, order-aligned with the
    input.  Identical findings in one report are disambiguated with an
    ``:N`` occurrence suffix, so a report with three instances of the
    same hazard baselines exactly three — a fourth still fails."""
    counts: Dict[str, int] = {}
    out: List[str] = []
    for f in findings:
        base = _fingerprint_base(f)
        n = counts.get(base, 0)
        counts[base] = n + 1
        out.append(base if n == 0 else f"{base}:{n}")
    return out


def apply_baseline(report: Report, fingerprints) -> None:
    """Drop findings whose fingerprint appears in ``fingerprints``
    (a committed baseline); the drop count lands on
    ``report.baselined``.  New findings — absent from the baseline —
    survive and still fail the run."""
    known = frozenset(fingerprints)
    keep: List[Finding] = []
    dropped = 0
    for f, fp in zip(report.findings, finding_fingerprints(report.findings)):
        if fp in known:
            dropped += 1
        else:
            keep.append(f)
    report.findings[:] = keep
    report.baselined += dropped


CACHE_VERSION = 2  # v2: LocalFacts gained execution-context fields


def _load_summary_cache(cache_path: str, entries) -> Dict[str, dict]:
    """``{path: {qual: LocalFacts}}`` for entries whose content hash
    matches the cache file; unreadable/stale/corrupt caches are just
    misses."""
    import hashlib

    from baton_tpu.analysis.summaries import LocalFacts

    try:
        data = json.loads(
            pathlib.Path(cache_path).read_text(encoding="utf-8")
        )
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("version") != CACHE_VERSION:
        return {}
    files = data.get("files", {})
    out: Dict[str, dict] = {}
    for path, source, _tree, _reg in entries:
        rec = files.get(path)
        if not isinstance(rec, dict):
            continue
        digest = hashlib.sha256(source.encode("utf-8")).hexdigest()
        if rec.get("hash") != digest:
            continue
        try:
            out[path] = {
                qual: LocalFacts.from_json(lf)
                for qual, lf in rec.get("functions", {}).items()
            }
        except (KeyError, TypeError, ValueError):
            continue
    return out


def _write_summary_cache(cache_path: str, project, summaries) -> None:
    import hashlib

    files = {}
    for mod in project.modules:
        facts = summaries.local_facts_by_path.get(mod.path)
        if facts is None:
            continue
        files[mod.path] = {
            "hash": hashlib.sha256(
                mod.source.encode("utf-8")
            ).hexdigest(),
            "functions": {
                qual: lf.to_json() for qual, lf in facts.items()
            },
        }
    payload = {"version": CACHE_VERSION, "files": files}
    try:
        pathlib.Path(cache_path).write_text(
            json.dumps(payload, sort_keys=True), encoding="utf-8"
        )
    except OSError:
        pass  # a read-only checkout must not fail the lint


def run_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    only_paths: Optional[Sequence[str]] = None,
    cache_path: Optional[str] = None,
) -> Report:
    """Lint files/directories; the CLI and test-suite entry point.

    All files are parsed into one :class:`Project` so project-scoped
    checkers (cross-module lock order) see the whole program.
    ``only_paths`` (the ``--changed-only`` filter) restricts the
    per-file pass and the REPORTED findings to those files while the
    project pass still reads everything.

    ``cache_path`` enables the incremental summary cache: per-function
    local facts are reloaded for files whose sha256 content hash is
    unchanged (skipping their extraction walk — the global fixpoint
    always reruns) and the file is rewritten after the run.  Hit/miss
    counts land on ``report.cache_hits``/``report.cache_misses``.
    """
    report = Report()
    registry_cache: Dict[str, Optional[dict]] = {}
    files = iter_python_files(paths)
    if not files:
        report.errors.append(f"no Python files under: {', '.join(paths)}")
        return report
    items = []
    for path in files:
        try:
            source = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as exc:
            report.errors.append(f"{path}: unreadable: {exc}")
            continue
        items.append(
            (str(path), source,
             _resolve_counter_registry(path, registry_cache))
        )
    entries = _parse_entries(items, report)
    only = (
        frozenset(_normalize_path(p) for p in only_paths)
        if only_paths is not None
        else None
    )
    project = _build_project(entries)
    if cache_path is not None:
        project._cached_local_facts = _load_summary_cache(
            cache_path, entries
        )
    _run_project(project, rules, report, only_paths=only)
    if cache_path is not None:
        from baton_tpu.analysis.summaries import get_summaries

        summaries = get_summaries(project)  # built by checkers or now
        report.cache_hits = len(summaries.cache_hits)
        report.cache_misses = len(summaries.cache_misses)
        _write_summary_cache(cache_path, project, summaries)
    return report


# -- reporters ---------------------------------------------------------
def format_text(report: Report) -> str:
    lines = [
        f"{f.location()}: {f.rule} {f.message}" for f in report.findings
    ]
    for err in report.errors:
        lines.append(f"error: {err}")
    baseline_note = (
        f", {report.baselined} baselined" if report.baselined else ""
    )
    lines.append(
        f"batonlint: {len(report.findings)} finding(s), "
        f"{report.suppressed} suppressed{baseline_note}, "
        f"{report.files_checked} file(s) checked"
    )
    return "\n".join(lines)


def format_json(report: Report) -> str:
    fps = finding_fingerprints(report.findings)
    findings = []
    for f, fp in zip(report.findings, fps):
        rec = f.to_json()
        rec["fingerprint"] = fp
        findings.append(rec)
    return json.dumps(
        {
            "findings": findings,
            "suppressed": report.suppressed,
            "files_checked": report.files_checked,
            "errors": list(report.errors),
            "baselined": report.baselined,
            "cache": {
                "hits": report.cache_hits,
                "misses": report.cache_misses,
            },
        },
        indent=2,
        sort_keys=True,
    )
