"""BTL010 — tracer hygiene inside jit / shard_map'd functions.

Code under ``jax.jit`` / ``shard_map`` runs ONCE at trace time against
abstract tracers; host-side operations inside it either crash
(``ConcretizationTypeError``), silently capture trace-time-only values,
or — worst — force a device sync per call. Flagged inside traced
functions (including their nested ``def``s and lambdas, which are
traced too):

* ``print(...)`` — runs at trace time only; use ``jax.debug.print``;
* ``.item()`` — concretizes a tracer, forcing a blocking transfer;
* ``float(x)`` / ``int(x)`` / ``bool(x)`` on values derived from the
  traced function's parameters — concretization;
* ``np.asarray`` / ``np.array`` / ``np.copy`` on parameter-derived
  values — silently materializes the tracer on host;
* module-state mutation (``global`` declarations, writes through
  module-level names) — trace-time side effects that do not replay.

A function counts as traced when it is decorated with
``jax.jit`` / ``jit`` / ``pmap`` / ``shard_map`` (bare or wrapped in
``partial(...)``), or when its name (or a lambda) is passed directly to
such a transform at a call site in the same module —
``jax.jit(one_client)``, ``shard_map(kernel, mesh, ...)``.

"Derived from the parameters" is intra-procedural dataflow taint, not
just name matching: taint starts at the parameters and propagates
through assignments (tuple unpacking included), ``self.*`` attribute
writes, container element writes and mutator calls (``d["k"] = x``,
``lst.append(x)`` taint the container), and call results (a call
consuming a traced value returns a traced value — the conservative
one-hop return rule), iterated to a fixpoint.  ``.shape``/``.dtype``/
``.ndim`` reads are static under tracing and cut the taint, so
``int(x.shape[0])`` stays legal.  ``self``/``cls`` themselves are NOT
tainted (a jitted method marks them static via ``static_argnums``);
only attributes explicitly written with traced values are.
"""

from __future__ import annotations

import ast
from typing import Callable, Iterable, List, Optional, Set

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

# dotted-name leaves that mark a JAX tracing transform
_TRANSFORMS = {"jit", "pmap", "shard_map", "vmap_of_jit"}

_NP_MATERIALIZERS = {"asarray", "array", "copy"}

_CASTS = {"float", "int", "bool", "complex"}

# attribute reads that are static (concrete) even on a tracer
_STATIC_ATTRS = {"shape", "dtype", "ndim"}

# container mutators whose tainted argument taints the receiver
_CONTAINER_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _make_taint_oracle(tainted: Set[str]) -> Callable[[ast.AST], bool]:
    """Predicate: does this expression produce a traced value, given
    the current taint set (bare names and dotted ``self.attr`` paths)?"""

    def expr_tainted(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            dotted = au.dotted_name(expr)
            if dotted is not None and dotted in tainted:
                return True
            return expr_tainted(expr.value)
        if isinstance(expr, _FUNC_NODES):
            return False
        if isinstance(expr, ast.Call):
            if expr_tainted(expr.func):
                return True
            return any(expr_tainted(a) for a in expr.args) or any(
                expr_tainted(k.value) for k in expr.keywords
            )
        return any(
            expr_tainted(child)
            for child in ast.iter_child_nodes(expr)
            if isinstance(child, ast.expr)
        )

    return expr_tainted


def _taint_target(target: ast.AST, add: Callable[[str], None]) -> None:
    """Record an assignment target as tainted: names directly, dotted
    ``self.x`` paths by path, container element writes by container."""
    if isinstance(target, ast.Name):
        add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _taint_target(elt, add)
    elif isinstance(target, ast.Starred):
        _taint_target(target.value, add)
    elif isinstance(target, ast.Attribute):
        dotted = au.dotted_name(target)
        if dotted is not None:
            add(dotted)
        else:
            _taint_target(target.value, add)
    elif isinstance(target, ast.Subscript):
        # d["k"] = tracer: reading ANY element of d may now yield it
        _taint_target(target.value, add)


def _propagate_taint(
    body: list, tainted: Set[str], expr_tainted
) -> bool:
    """One propagation pass over every statement (nested defs included
    — they trace as part of the same computation); True when the taint
    set grew."""
    changed = False

    def add(name: Optional[str]) -> None:
        nonlocal changed
        if name and name not in tainted:
            tainted.add(name)
            changed = True

    def call_args_tainted(call: ast.Call) -> bool:
        return any(expr_tainted(a) for a in call.args) or any(
            expr_tainted(k.value) for k in call.keywords
        )

    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                if expr_tainted(node.value):
                    for t in node.targets:
                        _taint_target(t, add)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if node.value is not None and (
                    expr_tainted(node.value)
                    or (
                        isinstance(node, ast.AugAssign)
                        and expr_tainted(node.target)
                    )
                ):
                    _taint_target(node.target, add)
            elif isinstance(node, ast.NamedExpr):
                if expr_tainted(node.value):
                    _taint_target(node.target, add)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if expr_tainted(node.iter):
                    _taint_target(node.target, add)
            elif isinstance(node, ast.withitem):
                if node.optional_vars is not None and expr_tainted(
                    node.context_expr
                ):
                    _taint_target(node.optional_vars, add)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CONTAINER_MUTATORS
                and call_args_tainted(node)
            ):
                _taint_target(node.func.value, add)
    return changed


def _transform_name(node: ast.AST) -> Optional[str]:
    """'jit'/'pmap'/'shard_map' when ``node`` names a JAX transform
    (``jit``, ``jax.jit``, ``jax.experimental.shard_map.shard_map``)."""
    name = au.dotted_name(node)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _TRANSFORMS:
        # guard against unrelated locals named e.g. `jit`: accept bare
        # names and anything rooted in jax/functools-style modules
        return leaf
    return None


def _decorator_transform(dec: ast.AST) -> Optional[str]:
    """Transform name when a decorator traces the function: ``@jax.jit``,
    ``@partial(jax.jit, static_argnums=...)``, ``@jit``."""
    direct = _transform_name(dec)
    if direct is not None:
        return direct
    if isinstance(dec, ast.Call):
        # @jax.jit(...) / @shard_map(...) factory form
        direct = _transform_name(dec.func)
        if direct is not None:
            return direct
        # @partial(jax.jit, ...) / @functools.partial(shard_map, ...)
        fname = au.dotted_name(dec.func)
        if fname is not None and fname.rsplit(".", 1)[-1] == "partial":
            if dec.args:
                return _transform_name(dec.args[0])
    return None


@register
class TracerHygieneChecker(Checker):
    rule = "BTL010"
    title = "host-side operation inside a jit/shard_map traced function"

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        module_names = self._module_level_names(ctx.tree)

        # name -> def node, for resolving jax.jit(one_client) call sites
        defs_by_name = {}
        for _qual, _cls, node in au.iter_function_defs(ctx.tree):
            defs_by_name.setdefault(node.name, node)

        traced: List[tuple] = []  # (node, how)
        seen_ids: Set[int] = set()

        def mark(node, how: str) -> None:
            if id(node) not in seen_ids:
                seen_ids.add(id(node))
                traced.append((node, how))

        for _qual, _cls, node in au.iter_function_defs(ctx.tree):
            for dec in node.decorator_list:
                t = _decorator_transform(dec)
                if t is not None:
                    mark(node, t)

        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call) or not call.args:
                continue
            t = _transform_name(call.func)
            if t is None:
                continue
            target = call.args[0]
            if isinstance(target, ast.Lambda):
                mark(target, t)
            elif isinstance(target, ast.Name) and target.id in defs_by_name:
                mark(defs_by_name[target.id], t)

        for node, how in traced:
            findings.extend(
                self._scan_traced(node, how, module_names, ctx)
            )
        return findings

    @staticmethod
    def _module_level_names(tree: ast.Module) -> Set[str]:
        names: Set[str] = set()
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _scan_traced(
        self, fn, how: str, module_names: Set[str], ctx: CheckContext
    ) -> List[Finding]:
        findings: List[Finding] = []
        label = getattr(fn, "name", "<lambda>")
        where = f"in `{label}` traced by {how}"

        # everything derived from the traced function's parameters is a
        # tracer; nested defs inherit the outer params (they are traced
        # as part of the same computation). self/cls are static under
        # jit (static_argnums), so only attributes written with traced
        # values taint — see _propagate_taint.
        tainted = au.param_names(fn) - {"self", "cls"}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, _FUNC_NODES):
                    tainted |= au.param_names(node) - {"self", "cls"}

        # intra-procedural dataflow: propagate taint through plain
        # assignments, tuple unpacking, `self.*` attributes, container
        # element writes (which taint the container), and call results
        # (any call consuming a traced value returns a traced value —
        # the conservative one-hop return rule). Iterate to a fixpoint:
        # `self._cache = x` early and `np.asarray(self._cache)` later
        # converge regardless of AST walk order.
        touches_tracer = _make_taint_oracle(tainted)
        for _ in range(10):  # fixpoint cap; real bodies settle in 2-3
            if not _propagate_taint(body, tainted, touches_tracer):
                break

        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Global):
                    findings.append(
                        Finding(
                            self.rule, ctx.path, node.lineno,
                            node.col_offset,
                            f"`global {', '.join(node.names)}` {where}: "
                            f"trace-time side effects do not replay on "
                            f"later calls",
                        )
                    )
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        root = t
                        while isinstance(root, (ast.Attribute, ast.Subscript)):
                            root = root.value
                        if (
                            t is not root  # only dotted/indexed writes
                            and isinstance(root, ast.Name)
                            and root.id in module_names
                        ):
                            findings.append(
                                Finding(
                                    self.rule, ctx.path, node.lineno,
                                    node.col_offset,
                                    f"mutation of module state "
                                    f"`{au.dotted_name(t) or root.id}` "
                                    f"{where}: happens once at trace "
                                    f"time, not per call",
                                )
                            )
                elif isinstance(node, ast.Call):
                    findings.extend(
                        self._check_call(node, where, touches_tracer, ctx)
                    )
        return findings

    def _check_call(self, call, where, touches_tracer, ctx):
        out = []
        name = au.call_name(call)
        if name == "print":
            out.append(
                Finding(
                    self.rule, ctx.path, call.lineno, call.col_offset,
                    f"print() {where} runs at trace time only; use "
                    f"jax.debug.print for per-call output",
                )
            )
        elif name in _CASTS and call.args and touches_tracer(call.args[0]):
            out.append(
                Finding(
                    self.rule, ctx.path, call.lineno, call.col_offset,
                    f"{name}() on a traced value {where} concretizes "
                    f"the tracer (ConcretizationTypeError or a forced "
                    f"device sync)",
                )
            )
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _NP_MATERIALIZERS
            and au.dotted_name(call.func.value) in ("np", "numpy")
            and call.args
            and touches_tracer(call.args[0])
        ):
            out.append(
                Finding(
                    self.rule, ctx.path, call.lineno, call.col_offset,
                    f"np.{call.func.attr}() on a traced value {where} "
                    f"materializes the tracer on host; use jnp.{call.func.attr}",
                )
            )
        elif isinstance(call.func, ast.Attribute) and call.func.attr == "item":
            if not call.args and not call.keywords:
                out.append(
                    Finding(
                        self.rule, ctx.path, call.lineno, call.col_offset,
                        f".item() {where} blocks on a device->host "
                        f"transfer per trace; return the array and "
                        f"concretize outside the jit boundary",
                    )
                )
        return out
