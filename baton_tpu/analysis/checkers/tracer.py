"""BTL010 — tracer hygiene inside jit / shard_map'd functions.

Code under ``jax.jit`` / ``shard_map`` runs ONCE at trace time against
abstract tracers; host-side operations inside it either crash
(``ConcretizationTypeError``), silently capture trace-time-only values,
or — worst — force a device sync per call. Flagged inside traced
functions (including their nested ``def``s and lambdas, which are
traced too):

* ``print(...)`` — runs at trace time only; use ``jax.debug.print``;
* ``.item()`` — concretizes a tracer, forcing a blocking transfer;
* ``float(x)`` / ``int(x)`` / ``bool(x)`` on values derived from the
  traced function's parameters — concretization;
* ``np.asarray`` / ``np.array`` / ``np.copy`` on parameter-derived
  values — silently materializes the tracer on host;
* module-state mutation (``global`` declarations, writes through
  module-level names) — trace-time side effects that do not replay;
* a call into any project function — same module, another module, or a
  ``self.method()`` through class-hierarchy dispatch — whose bottom-up
  fixpoint summary (:mod:`~baton_tpu.analysis.summaries`) contains one
  of the hazards above, at any depth.  ``print`` in a helper fires
  unconditionally (the helper's body is traced too); casts /
  materializers / ``.item()`` in a helper fire only when the call
  passes a traced argument, since they concretize the *caller's*
  tracer through the parameter.  The finding lands at the call site in
  the traced function and names the hazard's true location and witness
  chain.

A function counts as traced when it is decorated with
``jax.jit`` / ``jit`` / ``pmap`` / ``shard_map`` (bare or wrapped in
``partial(...)``), or when its name (or a lambda) is passed directly to
such a transform at a call site in the same module —
``jax.jit(one_client)``, ``shard_map(kernel, mesh, ...)``.

"Derived from the parameters" is intra-procedural dataflow taint, not
just name matching: taint starts at the parameters and propagates
through assignments (tuple unpacking included), ``self.*`` attribute
writes, container element writes and mutator calls (``d["k"] = x``,
``lst.append(x)`` taint the container), and call results (a call
consuming a traced value returns a traced value — the conservative
one-hop return rule), iterated to a fixpoint.  ``.shape``/``.dtype``/
``.ndim`` reads are static under tracing and cut the taint, so
``int(x.shape[0])`` stays legal.  ``self``/``cls`` themselves are NOT
tainted (a jitted method marks them static via ``static_argnums``);
only attributes explicitly written with traced values are.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.summaries import get_summaries

# dotted-name leaves that mark a JAX tracing transform
_TRANSFORMS = {"jit", "pmap", "shard_map", "vmap_of_jit"}

_NP_MATERIALIZERS = {"asarray", "array", "copy"}

_CASTS = {"float", "int", "bool", "complex"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _transform_name(node: ast.AST) -> Optional[str]:
    """'jit'/'pmap'/'shard_map' when ``node`` names a JAX transform
    (``jit``, ``jax.jit``, ``jax.experimental.shard_map.shard_map``)."""
    name = au.dotted_name(node)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _TRANSFORMS:
        # guard against unrelated locals named e.g. `jit`: accept bare
        # names and anything rooted in jax/functools-style modules
        return leaf
    return None


def _decorator_transform(dec: ast.AST) -> Optional[str]:
    """Transform name when a decorator traces the function: ``@jax.jit``,
    ``@partial(jax.jit, static_argnums=...)``, ``@jit``."""
    direct = _transform_name(dec)
    if direct is not None:
        return direct
    if isinstance(dec, ast.Call):
        # @jax.jit(...) / @shard_map(...) factory form
        direct = _transform_name(dec.func)
        if direct is not None:
            return direct
        # @partial(jax.jit, ...) / @functools.partial(shard_map, ...)
        fname = au.dotted_name(dec.func)
        if fname is not None and fname.rsplit(".", 1)[-1] == "partial":
            if dec.args:
                return _transform_name(dec.args[0])
    return None


@register
class TracerHygieneChecker(ProjectChecker):
    rule = "BTL010"
    title = "host-side operation inside a jit/shard_map traced function"

    def check_project(self, project) -> Iterable[Finding]:
        findings: List[Finding] = []
        summaries = get_summaries(project)
        for mod in project.modules:
            findings.extend(self._check_module(mod, project, summaries))
        return findings

    def _check_module(self, mod, project, summaries) -> List[Finding]:
        findings: List[Finding] = []
        module_names = self._module_level_names(mod.tree)

        # name -> (def node, class), for resolving jax.jit(one_client)
        defs_by_name = {}
        for _qual, cls, node in au.iter_function_defs(mod.tree):
            defs_by_name.setdefault(node.name, (node, cls))

        traced: List[tuple] = []  # (node, class_name, how)
        seen_ids: Set[int] = set()

        def mark(node, cls, how: str) -> None:
            if id(node) not in seen_ids:
                seen_ids.add(id(node))
                traced.append((node, cls, how))

        for _qual, cls, node in au.iter_function_defs(mod.tree):
            for dec in node.decorator_list:
                t = _decorator_transform(dec)
                if t is not None:
                    mark(node, cls, t)

        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call) or not call.args:
                continue
            t = _transform_name(call.func)
            if t is None:
                continue
            target = call.args[0]
            if isinstance(target, ast.Lambda):
                mark(target, None, t)
            elif isinstance(target, ast.Name) and target.id in defs_by_name:
                node, cls = defs_by_name[target.id]
                mark(node, cls, t)

        for node, cls, how in traced:
            findings.extend(
                self._scan_traced(
                    node, cls, how, module_names, mod, project, summaries
                )
            )
        return findings

    @staticmethod
    def _module_level_names(tree: ast.Module) -> Set[str]:
        names: Set[str] = set()
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _scan_traced(
        self, fn, cls, how, module_names, mod, project, summaries
    ) -> List[Finding]:
        findings: List[Finding] = []
        label = getattr(fn, "name", "<lambda>")
        where = f"in `{label}` traced by {how}"

        # everything derived from the traced function's parameters is a
        # tracer; nested defs inherit the outer params (they are traced
        # as part of the same computation). self/cls are static under
        # jit (static_argnums), so only attributes written with traced
        # values taint — see _propagate_taint.
        tainted = au.param_names(fn) - {"self", "cls"}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, _FUNC_NODES):
                    tainted |= au.param_names(node) - {"self", "cls"}

        # intra-procedural dataflow: propagate taint through plain
        # assignments, tuple unpacking, `self.*` attributes, container
        # element writes (which taint the container), and call results
        # (any call consuming a traced value returns a traced value —
        # the conservative one-hop return rule). Iterate to a fixpoint:
        # `self._cache = x` early and `np.asarray(self._cache)` later
        # converge regardless of AST walk order.
        touches_tracer = au.make_taint_oracle(tainted)
        for _ in range(10):  # fixpoint cap; real bodies settle in 2-3
            if not au.propagate_taint(body, tainted, touches_tracer):
                break

        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Global):
                    findings.append(
                        Finding(
                            self.rule, mod.path, node.lineno,
                            node.col_offset,
                            f"`global {', '.join(node.names)}` {where}: "
                            f"trace-time side effects do not replay on "
                            f"later calls",
                        )
                    )
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        root = t
                        while isinstance(root, (ast.Attribute, ast.Subscript)):
                            root = root.value
                        if (
                            t is not root  # only dotted/indexed writes
                            and isinstance(root, ast.Name)
                            and root.id in module_names
                        ):
                            findings.append(
                                Finding(
                                    self.rule, mod.path, node.lineno,
                                    node.col_offset,
                                    f"mutation of module state "
                                    f"`{au.dotted_name(t) or root.id}` "
                                    f"{where}: happens once at trace "
                                    f"time, not per call",
                                )
                            )
                elif isinstance(node, ast.Call):
                    findings.extend(
                        self._check_call(node, where, touches_tracer, mod)
                    )
                    findings.extend(
                        self._check_call_summary(
                            node, cls, where, touches_tracer,
                            mod, project, summaries,
                        )
                    )
        return findings

    def _check_call(self, call, where, touches_tracer, mod):
        out = []
        name = au.call_name(call)
        if name == "print":
            out.append(
                Finding(
                    self.rule, mod.path, call.lineno, call.col_offset,
                    f"print() {where} runs at trace time only; use "
                    f"jax.debug.print for per-call output",
                )
            )
        elif name in _CASTS and call.args and touches_tracer(call.args[0]):
            out.append(
                Finding(
                    self.rule, mod.path, call.lineno, call.col_offset,
                    f"{name}() on a traced value {where} concretizes "
                    f"the tracer (ConcretizationTypeError or a forced "
                    f"device sync)",
                )
            )
        elif (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _NP_MATERIALIZERS
            and au.dotted_name(call.func.value) in ("np", "numpy")
            and call.args
            and touches_tracer(call.args[0])
        ):
            out.append(
                Finding(
                    self.rule, mod.path, call.lineno, call.col_offset,
                    f"np.{call.func.attr}() on a traced value {where} "
                    f"materializes the tracer on host; use jnp.{call.func.attr}",
                )
            )
        elif isinstance(call.func, ast.Attribute) and call.func.attr == "item":
            if not call.args and not call.keywords:
                out.append(
                    Finding(
                        self.rule, mod.path, call.lineno, call.col_offset,
                        f".item() {where} blocks on a device->host "
                        f"transfer per trace; return the array and "
                        f"concretize outside the jit boundary",
                    )
                )
        return out

    def _check_call_summary(
        self, call, cls, where, touches_tracer, mod, project, summaries
    ):
        """Interprocedural leg: the callee's fixpoint summary carries
        the host ops reachable through it (with witness chains)."""
        out = []
        args_tainted = any(
            touches_tracer(a) for a in call.args
        ) or any(
            kw.value is not None and touches_tracer(kw.value)
            for kw in call.keywords
        )
        for callee in project.resolve_call_multi(mod, cls, call):
            summ = summaries.get(callee.key)
            if summ is None:
                continue
            for (path, line, _c), (
                needs, _kind, msg, chain,
            ) in sorted(summ.taint_ops.items()):
                if needs and not args_tainted:
                    continue
                full = (callee.qualname,) + chain
                via = " -> ".join(f"{q}()" for q in full)
                out.append(
                    Finding(
                        self.rule, mod.path, call.lineno, call.col_offset,
                        f"call {where} reaches a host-side op via {via} "
                        f"(at {path}:{line}): {msg}",
                    )
                )
        return out
