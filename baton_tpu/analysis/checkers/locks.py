"""BTL002 — awaits under asyncio locks, and lock-order cycles.

Holding a state lock across a network/queue await is a liveness
hazard: every other coroutine needing the lock stalls for a peer's
round-trip (or forever, against a dead peer), and with a second lock
in the picture an ABBA ordering deadlocks the loop outright.

Two sub-rules:

* an ``await`` of a network/queue primitive (aiohttp verbs,
  ``resp.json()``/``.read()``/``.text()``, queue ``get``/``put``/
  ``join``, ``asyncio.sleep``) inside ``async with <lock>:`` is flagged
  at the await — lexically, or when the lock is held across an ``await``
  of a project coroutine whose bottom-up fixpoint summary
  (:mod:`~baton_tpu.analysis.summaries`) performs a network await at any
  depth (the finding then names the remote site and the witness chain).
  Either way it is suppressible at the await/call line or at the
  ``async with`` header (one allow covers a deliberately-held block);
* lock-acquisition ORDER is a whole-program directed graph: acquiring
  B while holding A — directly, or anywhere down the static call graph
  (:mod:`~baton_tpu.analysis.callgraph`), across modules — adds edge
  A->B.  Any cycle in that graph is a potential deadlock and is
  reported once with every acquisition path that closes it, so a
  multi-hop cross-module ABBA pair shows both sides.

A "lock" is any ``async with`` context whose name ends with ``lock``
or ``mutex`` (``self._register_lock``, ``state_lock``, ...) — naming
convention as lint contract, same spirit as the counter registry.
Identities unify where references do: ``self._x_lock`` is
``RootClass._x_lock`` — the ROOT ancestor that introduces the
attribute, so an acquisition in an overriding subclass method unifies
with the base class's (class-hierarchy analysis); a module-global is
``pkg.mod.x_lock`` from its home module or through any import alias.
Locks reached through other objects' attributes stay module-local
(no type inference), so cycles through those are still unseen.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.project import FunctionInfo, ModuleInfo, Project
from baton_tpu.analysis.summaries import (  # noqa: F401  (re-exported)
    NETWORK_ATTRS,
    NETWORK_DOTTED,
    get_summaries,
    is_network_call,
    lock_identity,
)


def _lock_identity(
    expr: ast.AST,
    class_name: Optional[str],
    mod: ModuleInfo,
    project: Optional[Project] = None,
) -> Optional[str]:
    """Normalized project-wide lock identity for an ``async with``
    context expr, or None when the context is not a lock.  With a
    project, ``self._x_lock`` normalizes to the ROOT-ancestor class that
    first declares the attribute, so a lock acquired in an overriding
    subclass method unifies with the base class's acquisitions."""
    return lock_identity(expr, class_name, mod, project=project)


@dataclasses.dataclass
class _Acquisition:
    lock: str
    node: ast.AST                     # the async with
    held: Tuple[str, ...]             # locks already held at this site


@dataclasses.dataclass
class _Witness:
    """One observed A-held-while-acquiring-B ordering."""

    path: str
    line: int
    col: int
    chain: Tuple[str, ...]            # function qualnames, caller first
    also_line: Optional[int] = None   # acquisition header, for allows

    def describe(self) -> str:
        via = (
            f" (via {' -> '.join(self.chain)})"
            if len(self.chain) > 1 else ""
        )
        return f"{self.path}:{self.line}{via}"


@register
class LockDisciplineChecker(ProjectChecker):
    rule = "BTL002"
    title = "network await under an asyncio lock / lock-order cycle"

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        summaries = get_summaries(project)
        graph = summaries.graph
        # per function: lock acquisitions and the calls made under lock
        acquires: Dict[str, List[_Acquisition]] = {}
        held_calls: Dict[str, List[Tuple[Tuple[str, ...], ast.Call]]] = {}
        awaited: Dict[str, set] = {}  # ids of Call nodes directly awaited
        for fn in project.functions():
            acqs: List[_Acquisition] = []
            calls: List[Tuple[Tuple[str, ...], ast.Call]] = []
            aw: set = set()
            self._collect(
                fn.node.body, fn, project, acqs, calls, aw, (), findings
            )
            acquires[fn.key] = acqs
            held_calls[fn.key] = calls
            awaited[fn.key] = aw

        # multi-hop: awaiting a project coroutine under a lock executes
        # every network await in that coroutine's fixpoint summary while
        # the lock is held — same stall, one call frame removed.
        for fn in project.functions():
            for held, call in held_calls[fn.key]:
                if id(call) not in awaited[fn.key]:
                    continue  # bare coroutine creation: nothing runs yet
                for edge in graph.callees(fn.key):
                    if edge.node is not call:
                        continue
                    summ = summaries.get(edge.callee.key)
                    if summ is None or not summ.is_async:
                        continue
                    self._flag_summary_awaits(
                        fn, call, held, edge, summ, findings
                    )
        # locks each function may acquire transitively, with the call
        # chain and site that witnesses the acquisition
        trans_memo: Dict[str, Dict[str, Tuple[str, int, Tuple[str, ...]]]] = {}

        def trans(key: str, visiting: frozenset) -> Dict[str, tuple]:
            if key in trans_memo:
                return trans_memo[key]
            if key in visiting:
                return {}  # recursion cycle: partial result is fine
            fn = graph.functions[key]
            out: Dict[str, tuple] = {}
            for acq in acquires.get(key, []):
                out.setdefault(
                    acq.lock,
                    (fn.module.path, acq.node.lineno, (fn.qualname,)),
                )
            for edge in graph.callees(key):
                for lock, (p, l, chain) in trans(
                    edge.callee.key, visiting | {key}
                ).items():
                    out.setdefault(lock, (p, l, (fn.qualname,) + chain))
            trans_memo[key] = out
            return out

        # the global lock-order graph: edge A -> B with first witness
        order: Dict[Tuple[str, str], _Witness] = {}
        for fn in project.functions():
            for acq in acquires[fn.key]:
                for outer in acq.held:
                    if outer != acq.lock:
                        order.setdefault(
                            (outer, acq.lock),
                            _Witness(
                                fn.module.path, acq.node.lineno,
                                acq.node.col_offset, (fn.qualname,),
                            ),
                        )
            for held, call in held_calls[fn.key]:
                # ALL dispatch candidates for this call node: through
                # the class hierarchy a self.method() may land in any
                # subclass override, and a lock acquired only in the
                # override must still order after the held ones
                for edge in graph.callees(fn.key):
                    if edge.node is not call:
                        continue
                    for lock, (_p, _l, chain) in trans(
                        edge.callee.key, frozenset({fn.key})
                    ).items():
                        for outer in held:
                            if outer != lock:
                                order.setdefault(
                                    (outer, lock),
                                    _Witness(
                                        fn.module.path, call.lineno,
                                        call.col_offset,
                                        (fn.qualname,) + chain,
                                    ),
                                )

        findings.extend(self._report_cycles(order))
        return findings

    # -- lock-order cycle reporting ------------------------------------
    def _report_cycles(
        self, order: Dict[Tuple[str, str], _Witness]
    ) -> List[Finding]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in order:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for outs in adj.values():
            outs.sort()

        findings: List[Finding] = []
        reported: set = set()
        for start in sorted(adj):
            cycle = self._shortest_cycle(start, adj)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            edges = list(zip(cycle, cycle[1:] + cycle[:1]))
            witnesses = [order[e] for e in edges]
            primary = witnesses[0]
            path_desc = "; ".join(
                f"`{a}` held while acquiring `{b}` at {w.describe()}"
                for (a, b), w in zip(edges, witnesses)
            )
            ring = " -> ".join(f"`{x}`" for x in cycle + [cycle[0]])
            also = tuple(
                sorted(
                    {
                        line
                        for w in witnesses
                        for line in (w.line, w.also_line)
                        if line is not None
                        and w.path == primary.path
                        and line != primary.line
                    }
                )
            )
            findings.append(
                Finding(
                    self.rule, primary.path, primary.line, primary.col,
                    f"lock-order conflict: cycle {ring} — {path_desc} — "
                    f"an ABBA deadlock on the event loop",
                    also_lines=also,
                )
            )
        return findings

    @staticmethod
    def _shortest_cycle(
        start: str, adj: Dict[str, List[str]]
    ) -> Optional[List[str]]:
        """BFS for the shortest path start -> ... -> start; None when
        ``start`` is on no cycle."""
        frontier = [[start]]
        seen = set()
        while frontier:
            nxt = []
            for path in frontier:
                for succ in adj.get(path[-1], []):
                    if succ == start:
                        return path
                    if succ not in seen:
                        seen.add(succ)
                        nxt.append(path + [succ])
            frontier = nxt
        return None

    # -- per-function collection ---------------------------------------
    def _collect(
        self,
        stmts,
        fn: FunctionInfo,
        project: Project,
        acqs: List[_Acquisition],
        calls: List[Tuple[Tuple[str, ...], ast.Call]],
        awaited: set,
        held: Tuple[str, ...],
        findings: List[Finding],
    ) -> None:
        for stmt in stmts:
            self._visit(stmt, fn, project, acqs, calls, awaited,
                        held, findings)

    def _visit(
        self, node, fn, project, acqs, calls, awaited, held, findings
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate execution context
        if isinstance(node, ast.AsyncWith):
            new_held = held
            header = [i.context_expr for i in node.items] + [
                i.optional_vars for i in node.items
            ]
            for item in node.items:
                expr = item.context_expr
                lock = _lock_identity(
                    expr, fn.class_name, fn.module, project
                )
                if lock is not None:
                    acqs.append(_Acquisition(lock, node, new_held))
                    new_held = new_held + (lock,)
                elif (
                    held
                    and isinstance(expr, ast.Call)
                    and is_network_call(expr)
                ):
                    # async with session.get(...) under a lock is the
                    # same hazard as awaiting it
                    self._flag_network(expr, held, node, fn, findings)
            for child in ast.iter_child_nodes(node):
                if child not in header:
                    self._visit(child, fn, project, acqs, calls, awaited,
                                new_held, findings)
            return
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            awaited.add(id(node.value))
            if held and is_network_call(node.value):
                self._flag_network(node.value, held, None, fn, findings)
        if held and isinstance(node, ast.Call):
            calls.append((held, node))
        for child in ast.iter_child_nodes(node):
            self._visit(child, fn, project, acqs, calls, awaited,
                        held, findings)

    def _flag_summary_awaits(
        self, fn, call, held, edge, summ, findings
    ) -> None:
        for (path, line, _c), (display, chain) in sorted(
            summ.network_awaits.items()
        ):
            full = (edge.callee.qualname,) + chain
            via = " -> ".join(f"{q}()" for q in full)
            findings.append(
                Finding(
                    self.rule, fn.module.path,
                    call.lineno, call.col_offset,
                    f"await of network/queue primitive `{display}` "
                    f"(at {path}:{line}, reached via {via}) while "
                    f"holding lock `{held[-1]}` stalls every waiter "
                    f"for a peer round-trip",
                    also_lines=self._enclosing_lock_lines(fn, call),
                )
            )

    def _flag_network(self, call, held, _hdr, fn, findings) -> None:
        lock = held[-1]
        name = au.call_name(call) or f"<expr>.{call.func.attr}"
        findings.append(
            Finding(
                self.rule, fn.module.path, call.lineno, call.col_offset,
                f"await of network/queue primitive `{name}` while "
                f"holding lock `{lock}` stalls every waiter for a peer "
                f"round-trip",
                also_lines=self._enclosing_lock_lines(fn, call),
            )
        )

    @staticmethod
    def _enclosing_lock_lines(fn: FunctionInfo, call: ast.Call) -> tuple:
        """Lines of the ``async with <lock>`` headers enclosing ``call``
        — each is a valid suppression point for the await finding."""
        lines = []

        def rec(node, stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return False
            if node is call:
                lines.extend(stack)
                return True
            new_stack = stack
            if isinstance(node, ast.AsyncWith):
                new_stack = stack + [node.lineno]
            return any(
                rec(child, new_stack)
                for child in ast.iter_child_nodes(node)
            )

        rec(fn.node, [])
        return tuple(lines)
