"""BTL002 — awaits under asyncio locks, and lock-order cycles.

Holding a state lock across a network/queue await is a liveness
hazard: every other coroutine needing the lock stalls for a peer's
round-trip (or forever, against a dead peer), and with a second lock
in the picture an ABBA ordering deadlocks the loop outright.

Two sub-rules:

* an ``await`` of a network/queue primitive (aiohttp verbs,
  ``resp.json()``/``.read()``/``.text()``, queue ``get``/``put``/
  ``join``, ``asyncio.sleep``) lexically inside ``async with <lock>:``
  is flagged at the await, suppressible at either the await line or the
  ``async with`` header (one allow covers a deliberately-held block);
* lock-acquisition ORDER is a whole-program directed graph: acquiring
  B while holding A — directly, or anywhere down the static call graph
  (:mod:`~baton_tpu.analysis.callgraph`), across modules — adds edge
  A->B.  Any cycle in that graph is a potential deadlock and is
  reported once with every acquisition path that closes it, so a
  multi-hop cross-module ABBA pair shows both sides.

A "lock" is any ``async with`` context whose name ends with ``lock``
or ``mutex`` (``self._register_lock``, ``state_lock``, ...) — naming
convention as lint contract, same spirit as the counter registry.
Identities unify where references do: ``self._x_lock`` is
``Class._x_lock`` from any method, a module-global is
``pkg.mod.x_lock`` from its home module or through any import alias.
Locks reached through other objects' attributes stay module-local
(no type inference), so cycles through those are still unseen.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.callgraph import CallGraph
from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.project import FunctionInfo, ModuleInfo, Project

# attribute names that mean "this await leaves the process" (HTTP verb,
# body read, queue hand-off) — receiver-agnostic by design: sessions,
# responses and queues go by many names
NETWORK_ATTRS = {
    "get", "post", "put", "patch", "delete", "head", "request",
    "read", "text", "json", "recv", "receive", "send", "send_json",
    "fetch", "connect", "join", "drain",
}
NETWORK_DOTTED = {"asyncio.sleep"}


def _lock_identity(
    expr: ast.AST, class_name: Optional[str], mod: ModuleInfo
) -> Optional[str]:
    """Normalized project-wide lock identity for an ``async with``
    context expr, or None when the context is not a lock."""
    name = au.dotted_name(expr)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if not (leaf.endswith("lock") or leaf.endswith("mutex")):
        return None
    root, _, rest = name.partition(".")
    if root in ("self", "cls") and rest and class_name is not None:
        return f"{class_name}.{rest}"
    if rest:
        target = mod.imports.get(root)
        if target is not None:
            # module-global lock referenced through an import alias:
            # unify with its home-module bare name
            return f"{target}.{rest}"
        return f"{mod.name}:{name}"  # some other object's attribute
    return f"{mod.name}.{name}"


def _is_network_call(call: ast.Call) -> bool:
    dotted = au.call_name(call)
    if dotted in NETWORK_DOTTED:
        return True
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in NETWORK_ATTRS
    )


@dataclasses.dataclass
class _Acquisition:
    lock: str
    node: ast.AST                     # the async with
    held: Tuple[str, ...]             # locks already held at this site


@dataclasses.dataclass
class _Witness:
    """One observed A-held-while-acquiring-B ordering."""

    path: str
    line: int
    col: int
    chain: Tuple[str, ...]            # function qualnames, caller first
    also_line: Optional[int] = None   # acquisition header, for allows

    def describe(self) -> str:
        via = (
            f" (via {' -> '.join(self.chain)})"
            if len(self.chain) > 1 else ""
        )
        return f"{self.path}:{self.line}{via}"


@register
class LockDisciplineChecker(ProjectChecker):
    rule = "BTL002"
    title = "network await under an asyncio lock / lock-order cycle"

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        graph = CallGraph(project)
        # per function: lock acquisitions and the calls made under lock
        acquires: Dict[str, List[_Acquisition]] = {}
        held_calls: Dict[str, List[Tuple[Tuple[str, ...], ast.Call]]] = {}
        for fn in project.functions():
            acqs: List[_Acquisition] = []
            calls: List[Tuple[Tuple[str, ...], ast.Call]] = []
            self._collect(
                fn.node.body, fn, acqs, calls, (), findings
            )
            acquires[fn.key] = acqs
            held_calls[fn.key] = calls

        # locks each function may acquire transitively, with the call
        # chain and site that witnesses the acquisition
        trans_memo: Dict[str, Dict[str, Tuple[str, int, Tuple[str, ...]]]] = {}

        def trans(key: str, visiting: frozenset) -> Dict[str, tuple]:
            if key in trans_memo:
                return trans_memo[key]
            if key in visiting:
                return {}  # recursion cycle: partial result is fine
            fn = graph.functions[key]
            out: Dict[str, tuple] = {}
            for acq in acquires.get(key, []):
                out.setdefault(
                    acq.lock,
                    (fn.module.path, acq.node.lineno, (fn.qualname,)),
                )
            for edge in graph.callees(key):
                for lock, (p, l, chain) in trans(
                    edge.callee.key, visiting | {key}
                ).items():
                    out.setdefault(lock, (p, l, (fn.qualname,) + chain))
            trans_memo[key] = out
            return out

        # the global lock-order graph: edge A -> B with first witness
        order: Dict[Tuple[str, str], _Witness] = {}
        for fn in project.functions():
            for acq in acquires[fn.key]:
                for outer in acq.held:
                    if outer != acq.lock:
                        order.setdefault(
                            (outer, acq.lock),
                            _Witness(
                                fn.module.path, acq.node.lineno,
                                acq.node.col_offset, (fn.qualname,),
                            ),
                        )
            for held, call in held_calls[fn.key]:
                callee = next(
                    (e for e in graph.callees(fn.key) if e.node is call),
                    None,
                )
                if callee is None:
                    continue
                for lock, (_p, _l, chain) in trans(
                    callee.callee.key, frozenset({fn.key})
                ).items():
                    for outer in held:
                        if outer != lock:
                            order.setdefault(
                                (outer, lock),
                                _Witness(
                                    fn.module.path, call.lineno,
                                    call.col_offset,
                                    (fn.qualname,) + chain,
                                ),
                            )

        findings.extend(self._report_cycles(order))
        return findings

    # -- lock-order cycle reporting ------------------------------------
    def _report_cycles(
        self, order: Dict[Tuple[str, str], _Witness]
    ) -> List[Finding]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in order:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for outs in adj.values():
            outs.sort()

        findings: List[Finding] = []
        reported: set = set()
        for start in sorted(adj):
            cycle = self._shortest_cycle(start, adj)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            edges = list(zip(cycle, cycle[1:] + cycle[:1]))
            witnesses = [order[e] for e in edges]
            primary = witnesses[0]
            path_desc = "; ".join(
                f"`{a}` held while acquiring `{b}` at {w.describe()}"
                for (a, b), w in zip(edges, witnesses)
            )
            ring = " -> ".join(f"`{x}`" for x in cycle + [cycle[0]])
            also = tuple(
                sorted(
                    {
                        line
                        for w in witnesses
                        for line in (w.line, w.also_line)
                        if line is not None
                        and w.path == primary.path
                        and line != primary.line
                    }
                )
            )
            findings.append(
                Finding(
                    self.rule, primary.path, primary.line, primary.col,
                    f"lock-order conflict: cycle {ring} — {path_desc} — "
                    f"an ABBA deadlock on the event loop",
                    also_lines=also,
                )
            )
        return findings

    @staticmethod
    def _shortest_cycle(
        start: str, adj: Dict[str, List[str]]
    ) -> Optional[List[str]]:
        """BFS for the shortest path start -> ... -> start; None when
        ``start`` is on no cycle."""
        frontier = [[start]]
        seen = set()
        while frontier:
            nxt = []
            for path in frontier:
                for succ in adj.get(path[-1], []):
                    if succ == start:
                        return path
                    if succ not in seen:
                        seen.add(succ)
                        nxt.append(path + [succ])
            frontier = nxt
        return None

    # -- per-function collection ---------------------------------------
    def _collect(
        self,
        stmts,
        fn: FunctionInfo,
        acqs: List[_Acquisition],
        calls: List[Tuple[Tuple[str, ...], ast.Call]],
        held: Tuple[str, ...],
        findings: List[Finding],
    ) -> None:
        for stmt in stmts:
            self._visit(stmt, fn, acqs, calls, held, findings)

    def _visit(
        self, node, fn, acqs, calls, held, findings
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate execution context
        if isinstance(node, ast.AsyncWith):
            new_held = held
            header = [i.context_expr for i in node.items] + [
                i.optional_vars for i in node.items
            ]
            for item in node.items:
                expr = item.context_expr
                lock = _lock_identity(expr, fn.class_name, fn.module)
                if lock is not None:
                    acqs.append(_Acquisition(lock, node, new_held))
                    new_held = new_held + (lock,)
                elif (
                    held
                    and isinstance(expr, ast.Call)
                    and _is_network_call(expr)
                ):
                    # async with session.get(...) under a lock is the
                    # same hazard as awaiting it
                    self._flag_network(expr, held, node, fn, findings)
            for child in ast.iter_child_nodes(node):
                if child not in header:
                    self._visit(child, fn, acqs, calls, new_held, findings)
            return
        if held and isinstance(node, ast.Await):
            value = node.value
            if isinstance(value, ast.Call) and _is_network_call(value):
                self._flag_network(value, held, None, fn, findings)
        if held and isinstance(node, ast.Call):
            calls.append((held, node))
        for child in ast.iter_child_nodes(node):
            self._visit(child, fn, acqs, calls, held, findings)

    def _flag_network(self, call, held, _hdr, fn, findings) -> None:
        lock = held[-1]
        name = au.call_name(call) or f"<expr>.{call.func.attr}"
        findings.append(
            Finding(
                self.rule, fn.module.path, call.lineno, call.col_offset,
                f"await of network/queue primitive `{name}` while "
                f"holding lock `{lock}` stalls every waiter for a peer "
                f"round-trip",
                also_lines=self._enclosing_lock_lines(fn, call),
            )
        )

    @staticmethod
    def _enclosing_lock_lines(fn: FunctionInfo, call: ast.Call) -> tuple:
        """Lines of the ``async with <lock>`` headers enclosing ``call``
        — each is a valid suppression point for the await finding."""
        lines = []

        def rec(node, stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn.node:
                return False
            if node is call:
                lines.extend(stack)
                return True
            new_stack = stack
            if isinstance(node, ast.AsyncWith):
                new_stack = stack + [node.lineno]
            return any(
                rec(child, new_stack)
                for child in ast.iter_child_nodes(node)
            )

        rec(fn.node, [])
        return tuple(lines)
