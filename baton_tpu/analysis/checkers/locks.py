"""BTL002 — awaits under asyncio locks, and lock-order conflicts.

Holding a state lock across a network/queue await is a liveness
hazard: every other coroutine needing the lock stalls for a peer's
round-trip (or forever, against a dead peer), and with a second lock
in the picture an ABBA ordering deadlocks the loop outright.

Two sub-rules:

* an ``await`` of a network/queue primitive (aiohttp verbs,
  ``resp.json()``/``.read()``/``.text()``, queue ``get``/``put``/
  ``join``, ``asyncio.sleep``) lexically inside ``async with <lock>:``
  is flagged at the await, suppressible at either the await line or the
  ``async with`` header (one allow covers a deliberately-held block);
* lock-acquisition ORDER is collected per function — including locks
  acquired by same-module functions called while a lock is held — and
  any A-then-B vs B-then-A pair across the file is flagged.

A "lock" is any ``async with`` context whose name ends with ``lock``
or ``mutex`` (``self._register_lock``, ``state_lock``, ...) — naming
convention as lint contract, same spirit as the counter registry.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

# attribute names that mean "this await leaves the process" (HTTP verb,
# body read, queue hand-off) — receiver-agnostic by design: sessions,
# responses and queues go by many names
NETWORK_ATTRS = {
    "get", "post", "put", "patch", "delete", "head", "request",
    "read", "text", "json", "recv", "receive", "send", "send_json",
    "fetch", "connect", "join", "drain",
}
NETWORK_DOTTED = {"asyncio.sleep"}


def _lock_name(expr: ast.AST, class_name: Optional[str]) -> Optional[str]:
    """Normalized lock identity for an ``async with`` context expr, or
    None when the context is not a lock. ``self._x_lock`` in two
    methods of one class must compare equal -> ``Class._x_lock``."""
    name = au.dotted_name(expr)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if not (leaf.endswith("lock") or leaf.endswith("mutex")):
        return None
    if name.startswith("self.") and class_name is not None:
        return f"{class_name}.{name[len('self.'):]}"
    return name


def _is_network_call(call: ast.Call) -> bool:
    dotted = au.call_name(call)
    if dotted in NETWORK_DOTTED:
        return True
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in NETWORK_ATTRS
    )


@register
class LockDisciplineChecker(Checker):
    rule = "BTL002"
    title = "network await under an asyncio lock / lock-order conflict"

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        # func qualname -> [(lock, node)] locks it acquires at top level
        acquires: Dict[str, List[Tuple[str, ast.AST]]] = {}
        # (held, acquired) -> first location witnessing that order
        order: Dict[Tuple[str, str], Tuple[int, int]] = {}
        # (held_lock, lock_line, callee_qualname, call_node)
        held_calls: List[Tuple[str, int, str, ast.AST]] = []

        def visit_body(
            stmts, qual: str, cls: Optional[str],
            held: List[Tuple[str, int]],
        ) -> None:
            for stmt in stmts:
                self._visit_node(
                    stmt, qual, cls, held,
                    findings, acquires, order, held_calls, ctx,
                )

        for qual, cls, node in au.iter_function_defs(ctx.tree):
            acquires.setdefault(qual, [])
            visit_body(node.body, qual, cls, [])

        # interprocedural edges: calling f() while holding L orders L
        # before every lock f acquires (one hop is what real code does;
        # deeper chains would need whole-program analysis)
        for held, lock_line, callee, call in held_calls:
            for acquired, acq_node in acquires.get(callee, []):
                if acquired != held:
                    order.setdefault(
                        (held, acquired),
                        (call.lineno, call.col_offset),
                    )

        reported: Set[frozenset] = set()
        for (a, b), (line, col) in sorted(order.items()):
            if (b, a) in order and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                other_line, _ = order[(b, a)]
                findings.append(
                    Finding(
                        self.rule, ctx.path, line, col,
                        f"lock-order conflict: `{a}` is held while "
                        f"acquiring `{b}` here, but line {other_line} "
                        f"acquires them in the opposite order — an "
                        f"ABBA deadlock on the event loop",
                    )
                )
        return findings

    def _visit_node(
        self, node, qual, cls, held, findings, acquires, order,
        held_calls, ctx,
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate execution context
        if isinstance(node, ast.AsyncWith):
            new_held = list(held)
            for item in node.items:
                expr = item.context_expr
                lock = _lock_name(expr, cls)
                if lock is not None:
                    acquires[qual].append((lock, node))
                    for outer, _line in new_held:
                        if outer != lock:
                            order.setdefault(
                                (outer, lock),
                                (node.lineno, node.col_offset),
                            )
                    new_held.append((lock, node.lineno))
                elif (
                    held
                    and isinstance(expr, ast.Call)
                    and _is_network_call(expr)
                ):
                    # async with session.get(...) under a lock is the
                    # same hazard as awaiting it
                    self._flag_network(expr, held, findings, ctx)
            for child in ast.iter_child_nodes(node):
                if child not in (
                    [i.context_expr for i in node.items]
                    + [i.optional_vars for i in node.items]
                ):
                    self._visit_node(
                        child, qual, cls, new_held,
                        findings, acquires, order, held_calls, ctx,
                    )
            return
        if held and isinstance(node, ast.Await):
            value = node.value
            if isinstance(value, ast.Call) and _is_network_call(value):
                self._flag_network(value, held, findings, ctx)
        if held and isinstance(node, ast.Call):
            callee = au.resolve_local_call(node, cls)
            if callee is not None:
                innermost, line = held[-1]
                held_calls.append((innermost, line, callee, node))
        for child in ast.iter_child_nodes(node):
            self._visit_node(
                child, qual, cls, held,
                findings, acquires, order, held_calls, ctx,
            )

    def _flag_network(self, call, held, findings, ctx) -> None:
        lock, lock_line = held[-1]
        name = au.call_name(call) or f"<expr>.{call.func.attr}"
        findings.append(
            Finding(
                self.rule, ctx.path, call.lineno, call.col_offset,
                f"await of network/queue primitive `{name}` while "
                f"holding lock `{lock}` (acquired line {lock_line}) "
                f"stalls every waiter for a peer round-trip",
                also_lines=(lock_line,),
            )
        )
