"""BTL020 — uncapped request-body reads in aiohttp handlers.

``await request.read()`` / ``await request.json()`` buffer the entire
body in memory before any size check runs — one oversized (or
malicious) POST can OOM the manager and take the whole cohort down
with it. Every ingest path must go through
``baton_tpu.server.utils.read_body_capped`` /
``read_json_capped``, which enforce both a Content-Length precheck and
a streamed hard cut-off and surface a 413.

The rule flags awaited ``.read()`` / ``.json()`` / ``.text()`` /
``.post()`` calls on a receiver that names an aiohttp request
(``request``, ``req``, ``self.request``, ``web_request``) anywhere
under ``server/``. The capped helpers themselves carry a
``# batonlint: allow[BTL020]`` at the one spot that legitimately
performs the raw read.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

_BODY_METHODS = {"read", "json", "text", "post"}
_REQUEST_NAMES = {"request", "req", "web_request", "http_request"}


def _is_request_receiver(expr: ast.AST) -> bool:
    name = au.dotted_name(expr)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _REQUEST_NAMES


@register
class WireCapChecker(Checker):
    rule = "BTL020"
    title = "uncapped aiohttp request-body read in baton_tpu/server/"

    def applies_to(self, ctx: CheckContext) -> bool:
        return "server" in ctx.parts

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Await):
                continue
            call = node.value
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _BODY_METHODS
                and _is_request_receiver(func.value)
            ):
                continue
            recv = au.dotted_name(func.value)
            findings.append(
                Finding(
                    self.rule, ctx.path, call.lineno, call.col_offset,
                    f"uncapped `await {recv}.{func.attr}()` buffers an "
                    f"unbounded request body; use read_body_capped / "
                    f"read_json_capped (413 on oversize) or suppress "
                    f"with '# batonlint: allow[BTL020]'",
                )
            )
        return findings
