"""batonlint rule modules — importing this package registers them all.

Adding a checker: create a module here, subclass
:class:`baton_tpu.analysis.engine.Checker`, decorate it with
``@register``, and import the module below. Give the rule a stable
``BTLxxx`` id (001-009 event-loop, 010-019 JAX, 020-029 wire, 030-039
observability) and add known-bad/known-good fixtures to
``tests/test_analysis.py``.
"""

from baton_tpu.analysis.checkers import (  # noqa: F401
    alertrules,
    blocking,
    contexts,
    counters,
    deadcode,
    donation,
    exemplars,
    locks,
    races,
    runbooks,
    spans,
    staleness,
    suppressions,
    tracer,
    wirecap,
)
