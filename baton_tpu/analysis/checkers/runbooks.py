"""BTL034 — runbook rules must name a cataloged action with known params.

The runbook engine (``baton_tpu/obs/runbooks.py``) is rules-as-data:
an operator pack is a list of dict literals, and a rule whose
``action`` misspells a catalog entry — or whose ``params`` override a
key the action does not define — is rejected at parse time in the
server but only *at runtime*. A pack committed to a scenario file or
test fixture can carry the typo for weeks before anything loads it.
This checker moves that strictness to lint time: any dict literal that
*looks like* a runbook rule (string ``name`` + string ``action`` plus
at least one other rule key) is audited against the action catalog and
its per-action parameter schema, and its ``trigger`` block — when
present as a literal — is shape-checked (exactly ``{"alert": <str>}``,
or a metric form whose selector lives in an evaluable namespace,
``fleet.*`` included).

The catalog below intentionally DUPLICATES the runtime literals
(``RUNBOOK_ACTIONS`` / ``ACTION_PARAMS`` keys /
``derive_fleet_view``'s address list) instead of importing them: the
analysis layer must lint a checkout whose runtime package may not even
import (that is the point of a linter), same policy as every other
checker's mirrored constant. ``tests/test_analysis.py`` pins the two
copies against each other.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

#: mirror of obs/runbooks.py::RUNBOOK_ACTIONS
_ACTIONS = frozenset({
    "bias_cohort",
    "overprovision",
    "adaptive_deadline",
    "fedbuff_fallback",
    "pin_shapes",
})

#: mirror of obs/runbooks.py::ACTION_PARAMS keys, per action
_ACTION_PARAM_KEYS = {
    "bias_cohort": frozenset({"weight", "statuses"}),
    "overprovision": frozenset({"epsilon_max", "gain"}),
    "adaptive_deadline": frozenset({"quantile", "margin", "min_s", "max_s"}),
    "fedbuff_fallback": frozenset({"buffer_frac"}),
    "pin_shapes": frozenset({"quarantine"}),
}

#: keys (beyond name/action) that mark a dict literal as a runbook rule
_RULE_MARKERS = frozenset({
    "trigger", "for_s", "cooldown_s", "params", "description",
})

#: fleet.* addresses derive_fleet_view produces (obs/runbooks.py)
_FLEET_SERIES = frozenset({
    "clients",
    "active_clients",
    "healthy_frac",
    "slow_frac",
    "flaky_frac",
    "degrading_frac",
    "slow_or_flaky_frac",
    "churn_frac",
    "storm_clients",
})

#: rounds.* series shared with the alert evaluator (BTL033's list)
_ROUNDS_SERIES = frozenset({
    "tail",
    "straggler_rate",
    "duration_p95",
    "duration_p95_ratio",
    "recompile_storm_rounds",
    "mfu_mean",
    "mfu_ratio",
})


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dict_keys(node: ast.Dict) -> Optional[dict]:
    """``{key: value_node}`` for an all-literal-keyed Dict, else None
    (a ``**spread`` or computed key makes the shape unauditable)."""
    out = {}
    for k, v in zip(node.keys, node.values):
        name = _const_str(k)
        if name is None:
            return None
        out[name] = v
    return out


@register
class RunbookRuleChecker(Checker):
    rule = "BTL034"
    title = "runbook rule names an unknown action, param, or trigger shape"

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys = {}
            for k, v in zip(node.keys, node.values):
                name = _const_str(k)
                if name is not None:
                    keys[name] = v
            if "name" not in keys or "action" not in keys:
                continue
            if not (_RULE_MARKERS & set(keys)):
                continue  # not a runbook rule shape
            rule_name = _const_str(keys["name"]) or "?"
            for problem in self._audit(keys):
                findings.append(Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"runbook rule `{rule_name}`: {problem}",
                ))
        return findings

    def _audit(self, keys: dict) -> List[str]:
        problems: List[str] = []
        action = _const_str(keys["action"])
        if action is None:
            return problems  # dynamic action; nothing checkable
        if action not in _ACTIONS:
            problems.append(
                f"action `{action}` is not in the catalog "
                f"{sorted(_ACTIONS)} — the engine would reject the "
                f"pack at load"
            )
            return problems  # param schema is undefined for it
        params = keys.get("params")
        if isinstance(params, ast.Dict):
            pkeys = _dict_keys(params)
            if pkeys is not None:
                known = _ACTION_PARAM_KEYS[action]
                for pk in sorted(set(pkeys) - known):
                    problems.append(
                        f"param `{pk}` is not defined for action "
                        f"`{action}` (known: {sorted(known)}) — the "
                        f"override would never take effect; it is a "
                        f"parse error at load"
                    )
        trigger = keys.get("trigger")
        if isinstance(trigger, ast.Dict):
            tkeys = _dict_keys(trigger)
            if tkeys is not None:
                problems.extend(self._audit_trigger(tkeys))
        return problems

    def _audit_trigger(self, tkeys: dict) -> List[str]:
        if "alert" in tkeys:
            if set(tkeys) != {"alert"}:
                return [
                    "an alert trigger must be exactly `{\"alert\": "
                    "<rule name>}` — extra keys "
                    f"{sorted(set(tkeys) - {'alert'})} are rejected"
                ]
            return []
        if "metric" not in tkeys:
            return [
                "trigger needs either `alert` or a `metric`/`op`/"
                "`threshold` selector"
            ]
        metric = _const_str(tkeys["metric"])
        if metric is None:
            return []  # dynamic selector; nothing checkable
        if metric.startswith("fleet."):
            series = metric[len("fleet."):]
            if series in _FLEET_SERIES:
                return []
            return [
                f"`{metric}` is not a derived fleet series "
                f"(known: {sorted(_FLEET_SERIES)})"
            ]
        if metric.startswith("rounds."):
            series = metric[len("rounds."):]
            if series in _ROUNDS_SERIES:
                return []
            return [
                f"`{metric}` is not a derived rounds series "
                f"(known: {sorted(_ROUNDS_SERIES)})"
            ]
        if metric.startswith(("counter:", "gauge:", "timer:")):
            return []  # BTL033's registry audit owns these forms
        return [
            f"trigger selector `{metric}` is not in the evaluable "
            f"namespace (fleet.*/rounds.*/counter:/gauge:/timer:…)"
        ]
