"""BTL032 — declared-exemplar timers must observe with span context.

PR 9's fleet health plane links a histogram's worst recent observation
to its round trace: ``Metrics.observe(name, seconds, exemplar=…)``
stores the trace/span id of the p99 spike so an operator can jump from
"round_s regressed" straight to the offending round's trace document.
That linkage only works if every ``observe`` call site on an
exemplar-declared timer actually passes the context — one bare
``metrics.observe("round_s", dt)`` and the exemplar silently pins to
whichever *other* call site last beat it, and the p99→trace jump rots
without any test failing.

The set of timers that promise exemplars is declared next to the other
metric registries: ``DECLARED_EXEMPLAR_TIMERS`` in
``baton_tpu/utils/metrics.py``, parsed as an AST literal by the engine
(never imported) and handed to checkers via
``ctx.counter_registry["exemplar_timers"]``. Scoped to ``server/`` and
``loadgen/`` like BTL030 — utils code (the ``timer()`` context manager
itself) is the mechanism, not a call site.

Flagged:

- ``metrics.observe("round_s", dt)`` — no ``exemplar=`` at all.
- ``metrics.observe("round_s", dt, exemplar=None)`` — a literal None
  defeats the declaration; pass ``tracing.current_context()`` (which
  may *return* None outside a span — that is fine, the decision is
  made at runtime, not hardcoded at the call site).

Suppress a genuinely context-free site with
``# batonlint: allow[BTL032]``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register


@register
class ExemplarCoverageChecker(Checker):
    rule = "BTL032"
    title = "exemplar-declared timer observed without span context"

    def applies_to(self, ctx: CheckContext) -> bool:
        reg = ctx.counter_registry
        return (
            ("server" in ctx.parts or "loadgen" in ctx.parts)
            and reg is not None
            and reg.get("exemplar_timers") is not None
        )

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        declared = ctx.counter_registry["exemplar_timers"]
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr == "observe"
                and node.args
            ):
                continue
            name = node.args[0]
            if not (
                isinstance(name, ast.Constant)
                and isinstance(name.value, str)
                and name.value in declared
            ):
                continue
            exemplar = next(
                (kw.value for kw in node.keywords
                 if kw.arg == "exemplar"),
                None,
            )
            # a third positional arg is also an exemplar
            if exemplar is None and len(node.args) >= 3:
                exemplar = node.args[2]
            if exemplar is None:
                findings.append(Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"timer `{name.value}` is in "
                    f"DECLARED_EXEMPLAR_TIMERS but this observe() "
                    f"passes no exemplar= — pass "
                    f"tracing.current_context() (or the round's "
                    f"trace/span ids) so the p99 exemplar keeps "
                    f"linking to a trace",
                ))
            elif (
                isinstance(exemplar, ast.Constant)
                and exemplar.value is None
            ):
                findings.append(Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"timer `{name.value}` observe() hardcodes "
                    f"exemplar=None — that defeats the "
                    f"DECLARED_EXEMPLAR_TIMERS declaration; pass the "
                    f"active span context instead",
                ))
        return findings
