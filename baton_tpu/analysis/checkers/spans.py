"""BTL031 — span hygiene: close on all paths, propagate traceparent.

Two invariants from ``baton_tpu/utils/tracing.py``:

1. **Spans end on every path.** A manually started span
   (``sp = tracer.start_span(...)``) that is never ``.end()``-ed in a
   ``finally`` block leaks silently: the round's trace just misses the
   phase, and nothing fails. The blessed form is
   ``with tracer.span(...):`` (which ends on every exit path); a
   manual span is allowed only when some ``try/finally`` in the same
   function calls ``<var>.end(...)`` in its ``finally``.

2. **Outbound HTTP under an active span forwards ``traceparent``.**
   An ``aiohttp`` client call (``...session.get/post/put``) made
   inside a ``with ...span(...):`` block that does not build its
   headers through :func:`baton_tpu.utils.tracing.trace_headers`
   breaks the trace right at the process boundary — the worker's spans
   end up in a different trace and the round's timeline silently loses
   its remote half. The ``headers=`` kwarg must be a
   ``trace_headers(...)`` call, or a local name assigned from one in
   the same function.

Scoped to ``server/`` files, like BTL001/BTL030 — that is where the
distributed protocol lives.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

_HTTP_METHODS = {"get", "post", "put"}


def _is_span_call(node: ast.AST) -> bool:
    """``<anything>.span(...)`` — tracer.span / self.tracer.span."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "span"
    )


def _receiver_tail(node: ast.AST) -> Optional[str]:
    """Last identifier of the call receiver: ``self._session.post`` →
    ``_session``; ``sess.get`` → ``sess``."""
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Attribute):
            return base.attr
        if isinstance(base, ast.Name):
            return base.id
    return None


def _is_session_http_call(node: ast.AST) -> bool:
    """An aiohttp client-session verb call: ``....session.get/post/put``
    where the receiver's trailing name mentions a session. The name
    filter keeps ``dict.get`` / ``registry.get`` out of scope."""
    if not (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _HTTP_METHODS
    ):
        return False
    tail = _receiver_tail(node.func)
    return tail is not None and ("session" in tail.lower() or tail == "sess")


def _is_trace_headers_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "trace_headers"
    if isinstance(func, ast.Attribute):
        return func.attr == "trace_headers"
    return False


def _names_assigned_from_trace_headers(func_node: ast.AST) -> set:
    """Local names bound to a ``trace_headers(...)`` result anywhere in
    the function — accepts the two-statement form
    ``hdrs = trace_headers(...); session.post(..., headers=hdrs)``."""
    names = set()
    for node in ast.walk(func_node):
        if (
            isinstance(node, ast.Assign)
            and _is_trace_headers_call(node.value)
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    names.add(target.id)
    return names


def _finally_ended_names(func_node: ast.AST) -> set:
    """Names ``x`` with a ``x.end(...)`` call inside any ``finally``
    block of the function."""
    names = set()
    for node in ast.walk(func_node):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "end"
                    and isinstance(sub.func.value, ast.Name)
                ):
                    names.add(sub.func.value.id)
    return names


@register
class SpanHygieneChecker(Checker):
    rule = "BTL031"
    title = "span not closed on all paths / traceparent not forwarded"

    def applies_to(self, ctx: CheckContext) -> bool:
        return "server" in ctx.parts

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for qual, _cls, func_node in au.iter_function_defs(ctx.tree):
            findings.extend(self._check_manual_spans(ctx, func_node))
            findings.extend(self._check_propagation(ctx, func_node))
        return findings

    # -- invariant 1: manual spans closed in a finally ------------------
    def _check_manual_spans(self, ctx, func_node) -> Iterable[Finding]:
        ended = None  # computed lazily: most functions have no spans
        for node in ast.walk(func_node):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "start_span"
            ):
                continue
            if ended is None:
                ended = _finally_ended_names(func_node)
            target = node.targets[0] if len(node.targets) == 1 else None
            name = target.id if isinstance(target, ast.Name) else None
            if name is not None and name in ended:
                continue
            yield Finding(
                self.rule, ctx.path, node.lineno, node.col_offset,
                "manually started span is not closed on all paths: "
                "call `.end()` in a try/finally, or use "
                "`with tracer.span(...)`",
            )

    # -- invariant 2: traceparent on outbound calls under a span --------
    def _check_propagation(self, ctx, func_node) -> Iterable[Finding]:
        span_bodies = []
        for node in ast.walk(func_node):
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                _is_span_call(item.context_expr) for item in node.items
            ):
                span_bodies.append(node)
        if not span_bodies:
            return
        ok_names = _names_assigned_from_trace_headers(func_node)
        seen = set()
        for with_node in span_bodies:
            for stmt in with_node.body:
                for node in ast.walk(stmt):
                    if id(node) in seen or not _is_session_http_call(node):
                        continue
                    seen.add(id(node))
                    headers = next(
                        (
                            kw.value for kw in node.keywords
                            if kw.arg == "headers"
                        ),
                        None,
                    )
                    if headers is not None and (
                        _is_trace_headers_call(headers)
                        or (
                            isinstance(headers, ast.Name)
                            and headers.id in ok_names
                        )
                    ):
                        continue
                    yield Finding(
                        self.rule, ctx.path, node.lineno, node.col_offset,
                        "outbound HTTP call under an active span must "
                        "forward `traceparent`: pass "
                        "`headers=trace_headers(...)` "
                        "(baton_tpu/utils/tracing.py)",
                    )
