"""BTL030 — metric names must be declared in the registry.

Dashboards and the ops alert rules key on exact metric names; a typo
at an ``metrics.inc("updates_recieved")`` call site silently forks the
series and the alert never fires. Every counter name used under
``server/`` or ``loadgen/`` must appear in ``DECLARED_COUNTERS`` (or
match a prefix in
``DECLARED_COUNTER_PREFIXES``, for families built with f-strings),
every timer/histogram name observed via ``.observe()``/``.timer()`` in
``DECLARED_TIMERS``, and every gauge set via ``.set_gauge()`` in
``DECLARED_GAUGES`` — all in ``baton_tpu/utils/metrics.py``.

The registry is parsed as AST literals by the engine — linting never
imports package code — and handed to this checker via
``ctx.counter_registry`` (a normalized dict; legacy 2-tuple fixtures
disable the timer/gauge audits). Dynamic counter names (f-strings,
variables) are checked against the declared prefixes when the static
prefix of the f-string resolves, and skipped otherwise; timers and
gauges have no prefix families, so only static names are audited.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

_INC_METHODS = {"inc"}
_TIMER_METHODS = {"observe", "timer"}
_GAUGE_METHODS = {"set_gauge"}


def _static_prefix(node: ast.AST) -> Optional[str]:
    """The compile-time-known leading text of a counter-name argument:
    the whole string for a constant, the leading literal chunk for an
    f-string, None when nothing is statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def _name_args(node: ast.Call) -> list:
    """The metric-name argument, with conditional names unrolled:
    ``"a" if cond else "b"`` picks one of two metrics at runtime, so
    each branch is checked."""
    stack, args = [node.args[0]], []
    while stack:
        a = stack.pop()
        if isinstance(a, ast.IfExp):
            stack.extend((a.body, a.orelse))
        else:
            args.append(a)
    return args


@register
class CounterRegistryChecker(Checker):
    rule = "BTL030"
    title = "metric name not declared in utils/metrics.py registry"

    def applies_to(self, ctx: CheckContext) -> bool:
        # loadgen drives the server over HTTP and publishes its own
        # scenario_* series into the same dashboards, so its call sites
        # are audited against the same registry
        return (
            "server" in ctx.parts or "loadgen" in ctx.parts
        ) and ctx.counter_registry is not None

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        reg = ctx.counter_registry
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute) and node.args
            ):
                continue
            if func.attr in _INC_METHODS:
                findings.extend(self._check_counter(ctx, node, reg))
            elif func.attr in _TIMER_METHODS and reg["timers"] is not None:
                findings.extend(self._check_named(
                    ctx, node, reg["timers"], "timer", "DECLARED_TIMERS"
                ))
            elif func.attr in _GAUGE_METHODS and reg["gauges"] is not None:
                findings.extend(self._check_named(
                    ctx, node, reg["gauges"], "gauge", "DECLARED_GAUGES"
                ))
        return findings

    def _check_counter(self, ctx, node, reg) -> Iterable[Finding]:
        declared = reg["counters"]
        prefixes = reg["counter_prefixes"]
        for arg in _name_args(node):
            is_exact = isinstance(arg, ast.Constant)
            prefix = _static_prefix(arg)
            if prefix is None:
                continue  # fully dynamic name; nothing checkable
            if is_exact:
                if prefix in declared or any(
                    prefix.startswith(p) for p in prefixes
                ):
                    continue
            else:
                # f-string family: its literal head must extend one
                # of the declared prefixes (or a declared prefix
                # must extend it, for short heads like f"up_{x}")
                if any(
                    prefix.startswith(p) or p.startswith(prefix)
                    for p in prefixes
                ):
                    continue
            yield Finding(
                self.rule, ctx.path, node.lineno, node.col_offset,
                f"counter `{prefix}{'' if is_exact else '...'}` "
                f"is not declared in DECLARED_COUNTERS"
                f"{'' if is_exact else ' / DECLARED_COUNTER_PREFIXES'}"
                f" (baton_tpu/utils/metrics.py); declare it or "
                f"fix the typo",
            )

    def _check_named(
        self, ctx, node, declared, kind, registry_name
    ) -> Iterable[Finding]:
        # timers/gauges have no runtime-suffix families: only exact
        # static names are audited, dynamic names are skipped
        for arg in _name_args(node):
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                continue
            if arg.value in declared:
                continue
            yield Finding(
                self.rule, ctx.path, node.lineno, node.col_offset,
                f"{kind} `{arg.value}` is not declared in "
                f"{registry_name} (baton_tpu/utils/metrics.py); "
                f"declare it or fix the typo",
            )
