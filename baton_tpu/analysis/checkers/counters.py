"""BTL030 — metrics counter names must be declared in the registry.

Dashboards and the ops alert rules key on exact counter names; a typo
at an ``metrics.inc("updates_recieved")`` call site silently forks the
series and the alert never fires. Every counter name used under
``server/`` must appear in ``DECLARED_COUNTERS`` (or match a prefix in
``DECLARED_COUNTER_PREFIXES``, for families built with f-strings) in
``baton_tpu/utils/metrics.py``.

The registry is parsed as AST literals by the engine — linting never
imports package code — and handed to this checker via
``ctx.counter_registry``. Dynamic counter names (f-strings, variables)
are checked against the declared prefixes when the static prefix of
the f-string resolves, and skipped otherwise.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

_INC_METHODS = {"inc"}


def _static_prefix(node: ast.AST) -> Optional[str]:
    """The compile-time-known leading text of a counter-name argument:
    the whole string for a constant, the leading literal chunk for an
    f-string, None when nothing is statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


@register
class CounterRegistryChecker(Checker):
    rule = "BTL030"
    title = "metrics counter not declared in utils/metrics.py registry"

    def applies_to(self, ctx: CheckContext) -> bool:
        return "server" in ctx.parts and ctx.counter_registry is not None

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        declared, prefixes = ctx.counter_registry
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in _INC_METHODS
                and node.args
            ):
                continue
            # a conditional name picks one of two counters at runtime:
            # check each branch ("a" if cond else "b")
            stack, args = [node.args[0]], []
            while stack:
                a = stack.pop()
                if isinstance(a, ast.IfExp):
                    stack.extend((a.body, a.orelse))
                else:
                    args.append(a)
            for arg in args:
                is_exact = isinstance(arg, ast.Constant)
                prefix = _static_prefix(arg)
                if prefix is None:
                    continue  # fully dynamic name; nothing checkable
                if is_exact:
                    if prefix in declared or any(
                        prefix.startswith(p) for p in prefixes
                    ):
                        continue
                else:
                    # f-string family: its literal head must extend one
                    # of the declared prefixes (or a declared prefix
                    # must extend it, for short heads like f"up_{x}")
                    if any(
                        prefix.startswith(p) or p.startswith(prefix)
                        for p in prefixes
                    ):
                        continue
                findings.append(
                    Finding(
                        self.rule, ctx.path, node.lineno, node.col_offset,
                        f"counter `{prefix}{'' if is_exact else '...'}` "
                        f"is not declared in DECLARED_COUNTERS"
                        f"{'' if is_exact else ' / DECLARED_COUNTER_PREFIXES'}"
                        f" (baton_tpu/utils/metrics.py); declare it or "
                        f"fix the typo",
                    )
                )
        return findings
