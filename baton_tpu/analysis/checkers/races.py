"""BTL004 — async shared-state race on ``self.*`` across an await.

asyncio gives every handler a free atomicity guarantee: between two
awaits, nothing else runs on the loop.  Both sub-patterns here are
exactly the ways server code forfeits that guarantee:

**Lost-update window** (lock-free, the ``http_manager`` shape)::

    waiters = self._waiters          # snapshot
    ...
    await self._flush(...)           # suspension: other tasks run
    self._waiters = waiters + [w]    # write-back from the STALE name

Any mutation of ``self._waiters`` performed by a task scheduled during
the suspension is silently overwritten.  Flagged when a local name
snapshots a ``self.*`` attribute, the function suspends, and the
attribute is later assigned an expression built from that stale name —
with no fresh re-read into the name and no ``is``/``is not`` identity
re-check in between.  Writes under a held asyncio lock are exempt (the
lock, not re-reading, is then the protocol — see the second pattern).

**Guarded window with a lockless accessor**::

    async with self._state_lock:     # M1: lock held ACROSS an await
        self._epoch += 1
        await self._rebalance()      #   mid-update state is observable
        self._assignments = new
    ...
    return self._assignments[k]      # M2: read WITHOUT the lock

A critical section that never suspends is loop-atomic, so lockless
readers are fine — the hazard appears exactly when the section holds
the lock across an await (that is when other tasks can run and observe
``self._epoch`` bumped but ``self._assignments`` still old).  Flagged:
any ``self.A`` access outside lock ``L`` in a class where some method
writes ``A`` under ``L`` and (per the fixpoint summaries, so the await
may live in a transitive callee) holds ``L`` across a suspension.
``__init__``/``__post_init__`` are construction-time and exempt, as is
the degenerate single-method case (writer and only accessor are the
same code under the same lock).

Lost-update scanning is **loop-sensitive**: a ``for``/``while``/
``async for`` whose body suspends is visited twice, the second pass
entering with the state the first pass left and every live snapshot
marked stale at the loop header — a snapshot hoisted above the loop is
fresh on iteration 1 but every later iteration writes back through a
value from a previous epoch.  Findings surfaced only by the repass
carry loop-carried wording.

Scope: classes in ``server/`` modules, asyncio only — ``threading``
locks (``with``, not ``async with``) guard true parallelism and are a
different rule's business.  Lock identities unify through the class
hierarchy (a lock acquired in a subclass override guards the base
attribute), and happens-before facts come from
:mod:`baton_tpu.analysis.summaries`, so both patterns see through
helper calls.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.summaries import get_summaries, lock_identity

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_SUSPENDERS = (ast.Await, ast.AsyncFor)
_CTOR_NAMES = {"__init__", "__post_init__", "__set_name__"}


def _body_suspends(stmts: List[ast.stmt]) -> bool:
    """True when a loop body can suspend the task (await / async for /
    async with anywhere in it, nested functions excluded)."""
    todo: List[ast.AST] = list(stmts)
    while todo:
        n = todo.pop()
        if isinstance(n, _FUNCS):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        todo.extend(ast.iter_child_nodes(n))
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


@dataclasses.dataclass
class _Access:
    fn: object                       # FunctionInfo
    attr: str
    line: int
    col: int
    is_write: bool
    locks: FrozenSet[str]            # normalized ids held lexically


class _Snapshot:
    __slots__ = ("attr", "line", "stale_since", "dead")

    def __init__(self, attr: str, line: int) -> None:
        self.attr = attr
        self.line = line
        self.stale_since: Optional[int] = None
        self.dead = False


@register
class AsyncRaceChecker(ProjectChecker):
    rule = "BTL004"
    title = "self.* state raced across an await (lost update / lockless read)"

    def check_project(self, project) -> Iterable[Finding]:
        findings: List[Finding] = []
        summaries = get_summaries(project)
        for mod in project.modules:
            if "server" not in mod.parts:
                continue
            by_class: Dict[str, List] = {}
            for fn in mod.functions.values():
                if fn.class_name is not None:
                    by_class.setdefault(fn.class_name, []).append(fn)
            for class_name, methods in by_class.items():
                self._check_class(
                    mod, class_name, methods, project, summaries, findings
                )
        return findings

    # ------------------------------------------------------------------
    def _check_class(
        self, mod, class_name, methods, project, summaries, findings
    ) -> None:
        accesses: List[_Access] = []
        for fn in methods:
            accesses.extend(
                self._collect_accesses(fn, class_name, mod, project)
            )

        self._check_guarded_windows(
            mod, class_name, methods, accesses, summaries, findings
        )
        for fn in methods:
            if not isinstance(fn.node, ast.AsyncFunctionDef):
                continue
            if fn.node.name.split(".")[-1] in _CTOR_NAMES:
                continue
            self._scan_lost_updates(
                fn, class_name, mod, project, findings
            )

    # -- pattern 2: guarded window + lockless accessor ------------------
    def _check_guarded_windows(
        self, mod, class_name, methods, accesses, summaries, findings
    ) -> None:
        # attr -> {lock: writer_fn} where the writer holds `lock` across
        # a suspension somewhere in its frame (transitively, per the
        # summaries) AND writes attr under it lexically
        guards: Dict[str, Dict[str, object]] = {}
        for acc in accesses:
            if not acc.is_write or not acc.locks:
                continue
            summ = summaries.for_function(acc.fn)
            if summ is None:
                continue
            for lock in acc.locks:
                if lock in summ.awaits_held:
                    guards.setdefault(acc.attr, {}).setdefault(
                        lock, acc.fn
                    )
        if not guards:
            return
        for acc in accesses:
            # only lockless WRITES: a single-attr read between awaits
            # sees a loop-consistent snapshot (asyncio's free atomicity,
            # and protocols like 401->refresh tolerate staleness), but a
            # lockless write voids the mutual exclusion the locked
            # writer paid for — its update can land mid-handshake or be
            # clobbered by it
            if not acc.is_write:
                continue
            if acc.fn.qualname.split(".")[-1] in _CTOR_NAMES:
                continue
            locked = guards.get(acc.attr)
            if not locked:
                continue
            missing = [
                (lock, writer)
                for lock, writer in sorted(locked.items())
                if lock not in acc.locks and writer.key != acc.fn.key
            ]
            if not missing:
                continue
            lock, writer = missing[0]
            findings.append(
                Finding(
                    self.rule, mod.path, acc.line, acc.col,
                    f"`self.{acc.attr}` is written here without "
                    f"`{lock}`, but `{writer.qualname}` mutates it "
                    f"with that lock held across an await — this "
                    f"write can interleave with that in-flight update "
                    f"(clobbering it or being clobbered); guard it, or "
                    f"compare-and-invalidate against the value the "
                    f"decision was based on",
                )
            )

    def _collect_accesses(
        self, fn, class_name, mod, project
    ) -> List[_Access]:
        out: List[_Access] = []

        def lock_of(expr) -> Optional[str]:
            return lock_identity(expr, class_name, mod, project)

        def visit(node, held: FrozenSet[str]) -> None:
            if isinstance(node, _FUNCS):
                return
            if isinstance(node, ast.AsyncWith):
                new_held = held
                for item in node.items:
                    lid = lock_of(item.context_expr)
                    if lid is not None:
                        new_held = new_held | {lid}
                    else:
                        visit(item.context_expr, held)
                for child in node.body:
                    visit(child, new_held)
                return
            attr = _self_attr(node)
            if attr is not None:
                out.append(_Access(
                    fn, attr, node.lineno, node.col_offset,
                    isinstance(node.ctx, (ast.Store, ast.Del)), held,
                ))
            # container mutation through self.A.append(...) is a write
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                recv = node.func.value
                r_attr = _self_attr(recv)
                if (
                    r_attr is not None
                    and node.func.attr in au.CONTAINER_MUTATORS | {
                        "pop", "popitem", "remove", "discard", "clear",
                    }
                ):
                    out.append(_Access(
                        fn, r_attr, node.lineno, node.col_offset,
                        True, held,
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.node.body:
            visit(stmt, frozenset())
        return out

    # -- pattern 1: lock-free lost-update window -------------------------
    def _scan_lost_updates(
        self, fn, class_name, mod, project, findings
    ) -> None:
        snapshots: Dict[str, _Snapshot] = {}
        loop_repass = [0]
        flagged_sites: Set[Tuple[int, int, str]] = set()

        def lock_of(expr) -> Optional[str]:
            return lock_identity(expr, class_name, mod, project)

        def walk_expr(e):
            todo = [e]
            while todo:
                n = todo.pop()
                yield n
                if not isinstance(n, _FUNCS):
                    todo.extend(ast.iter_child_nodes(n))

        def exprs_of(stmt) -> List[ast.AST]:
            if isinstance(stmt, (ast.If, ast.While)):
                return [stmt.test]
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                return [stmt.target, stmt.iter]
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                return [i.context_expr for i in stmt.items]
            if isinstance(stmt, ast.Try):
                return []
            if isinstance(stmt, _FUNCS + (ast.ClassDef,)):
                return []
            return [stmt]

        def has_suspend(nodes) -> Optional[int]:
            for e in nodes:
                for n in walk_expr(e):
                    if isinstance(n, _SUSPENDERS):
                        return n.lineno
            return None

        def uses_name(expr, name: str) -> bool:
            return any(
                isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Load)
                for n in walk_expr(expr)
            )

        def revalidated(nodes) -> Set[str]:
            out: Set[str] = set()
            for e in nodes:
                for n in walk_expr(e):
                    if not isinstance(n, ast.Compare):
                        continue
                    if not all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops
                    ):
                        continue
                    operands = [n.left] + list(n.comparators)
                    non_none = [
                        o for o in operands
                        if not (isinstance(o, ast.Constant)
                                and o.value is None)
                    ]
                    if len(non_none) < 2:
                        continue
                    for o in operands:
                        if isinstance(o, ast.Name):
                            out.add(o.id)
            return out

        def flag(name: str, snap: _Snapshot, stmt) -> None:
            snap.dead = True
            site = (stmt.lineno, stmt.col_offset, name)
            if site in flagged_sites:
                return  # already reported on an earlier loop pass
            flagged_sites.add(site)
            carried = (
                " (loop-carried: the snapshot is taken once but the "
                "loop body suspends, so every iteration after the "
                "first writes back through a stale value)"
                if loop_repass[0] else ""
            )
            findings.append(
                Finding(
                    self.rule, mod.path, stmt.lineno, stmt.col_offset,
                    f"lost-update window on `self.{snap.attr}` in "
                    f"`{fn.qualname}`: `{name}` snapshots it on line "
                    f"{snap.line}, the task suspends at the await on "
                    f"line {snap.stale_since}, and the write here "
                    f"rebuilds `self.{snap.attr}` from the stale "
                    f"`{name}` — a concurrent task's update during the "
                    f"suspension is silently overwritten; re-read "
                    f"`self.{snap.attr}` after the await (or mutate it "
                    f"in place / guard the window with a lock)" + carried,
                    also_lines=tuple(
                        x for x in (snap.line, snap.stale_since)
                        if x is not None
                    ),
                )
            )

        def visit(stmts, held: FrozenSet[str]) -> None:
            for stmt in stmts:
                if isinstance(stmt, _FUNCS + (ast.ClassDef,)):
                    continue
                header = exprs_of(stmt)

                for name in revalidated(header):
                    snap = snapshots.get(name)
                    if snap is not None:
                        snap.stale_since = None

                # stale write-back: self.A = f(name) / self.A += name
                if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    targets = (
                        stmt.targets if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        attr = _self_attr(t)
                        if attr is None or stmt.value is None:
                            continue
                        for name, snap in snapshots.items():
                            if (
                                snap.attr == attr
                                and not snap.dead
                                and snap.stale_since is not None
                                and not held  # locked windows: BTL004b
                                and uses_name(stmt.value, name)
                            ):
                                flag(name, snap, stmt)

                line = has_suspend(header)
                if line is not None:
                    for snap in snapshots.values():
                        if not snap.dead and snap.stale_since is None:
                            snap.stale_since = line

                # (re)bindings: `name = self.A` starts/refreshes a
                # snapshot; any other rebinding stops tracking
                fresh: Set[str] = set()
                if isinstance(stmt, ast.Assign):
                    attr = _self_attr(stmt.value)
                    if attr is not None:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                snapshots[t.id] = _Snapshot(
                                    attr, stmt.lineno
                                )
                                fresh.add(t.id)
                assigned: Set[str] = set()
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            assigned.add(t.id)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    if isinstance(stmt.target, ast.Name):
                        assigned.add(stmt.target.id)
                for name in assigned - fresh:
                    snapshots.pop(name, None)

                if isinstance(stmt, ast.AsyncWith):
                    new_held = held
                    for item in stmt.items:
                        lid = lock_of(item.context_expr)
                        if lid is not None:
                            new_held = new_held | {lid}
                    visit(stmt.body, new_held)
                    continue
                # loops whose body suspends: repass with iteration 1's
                # end state — a snapshot hoisted above the loop feeds a
                # repeated lost-update window on iterations 2+
                if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    visit(stmt.body, held)
                    if isinstance(stmt, ast.AsyncFor) or _body_suspends(
                        stmt.body
                    ):
                        for snap in snapshots.values():
                            if not snap.dead and snap.stale_since is None:
                                snap.stale_since = stmt.lineno
                        loop_repass[0] += 1
                        try:
                            visit(stmt.body, held)
                        finally:
                            loop_repass[0] -= 1
                    visit(stmt.orelse, held)
                    continue
                for block in (
                    getattr(stmt, "body", None),
                    getattr(stmt, "orelse", None),
                    getattr(stmt, "finalbody", None),
                ):
                    if isinstance(block, list):
                        visit(block, held)
                for handler in getattr(stmt, "handlers", []) or []:
                    visit(handler.body, held)

        visit(fn.node.body, frozenset())
