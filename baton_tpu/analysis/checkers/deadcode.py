"""BTL007 — functions reachable from no entry point.

The entry-point model (see :mod:`~baton_tpu.analysis.summaries`) makes
"reachable" meaningful for an event-loop server: a handler nobody
routes, a callback nobody schedules, a helper nobody calls is dead
weight that still costs review attention — and a dead *handler* is
usually a wiring bug, not tidiness.

Roots: public functions and methods (no leading ``_``) and dunders —
they ARE the module's API; decorated functions (registration
decorators run at import); every callable referenced by an entry-point
registration (routes, ``PeriodicTask``, loop callbacks, thread
dispatch); names referenced at module level (including ``__all__``
strings and class-body assignments); and functions named by another
module's imports.  From those roots the checker walks the call graph —
which, post reflection resolution, includes ``getattr``-prefix and
dispatch-table edges — plus by-value name references (callbacks passed
as arguments: ``map(self._f, xs)``, ``partial(self._f)``), so a
function is flagged only when *no* statically visible path roots it.

Because nested ``def``s and lambdas share the enclosing function's
lexical scope — and the call graph intentionally does not model
closures — reference collection for a *reached* function scans its
whole subtree (nested bodies included, call names included) and roots
any same-module function whose bare name is mentioned.  Coarse on
purpose: a dead-code rule must err toward silence.

Only private (leading ``_``, non-dunder) functions are flagged, at
their ``def`` line; suppress deliberate keep-arounds with
``# batonlint: allow[BTL007]``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.summaries import get_summaries

_SKIP = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _module_level_refs(mod) -> Set[str]:
    """Raw name/dotted refs made by module-scope code (class bodies
    included, function bodies excluded) plus ``__all__`` strings."""
    refs: Set[str] = set()

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SKIP):
                continue
            if isinstance(child, ast.Name) and isinstance(
                child.ctx, ast.Load
            ):
                refs.add(child.id)
            elif isinstance(child, ast.Attribute):
                d = None
                if isinstance(child.value, ast.Name):
                    d = f"{child.value.id}.{child.attr}"
                if d is not None:
                    refs.add(d)
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == "__all__"
                        and isinstance(child.value, (ast.List, ast.Tuple))
                    ):
                        refs.update(
                            e.value for e in child.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        )
            walk(child)

    walk(mod.tree)
    return refs


def _subtree_names(fn) -> Set[str]:
    """Every bare name a function's subtree mentions: Name loads and
    attribute names, nested defs and lambdas INCLUDED — closures see the
    enclosing scope, so a mention anywhere in the subtree keeps a
    same-scope helper alive."""
    names: Set[str] = set()
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
    return names


@register
class DeadCodeChecker(ProjectChecker):
    rule = "BTL007"
    title = (
        "function reachable from no entry point (dead handler or "
        "orphaned helper)"
    )

    def check_project(self, project) -> Iterable[Finding]:
        summ = get_summaries(project)
        graph = summ.graph

        roots: List[str] = []
        for fn in project.functions():
            bare = fn.node.name
            is_dunder = bare.startswith("__") and bare.endswith("__")
            if not bare.startswith("_") or is_dunder:
                roots.append(fn.key)
            elif fn.node.decorator_list:
                roots.append(fn.key)

        for fn in project.functions():
            lf = summ.locals.get(fn.key)
            if lf is None:
                continue
            for _kind, ref, _line in lf.entry_regs:
                for target in project.resolve_ref(
                    fn.module, fn.class_name, ref
                ):
                    roots.append(target.key)

        for mod in project.modules:
            for ref in _module_level_refs(mod):
                for target in project.resolve_ref(mod, None, ref):
                    roots.append(target.key)
            for dotted in mod.imports.values():
                target = project.function_by_dotted(dotted)
                if target is not None:
                    roots.append(target.key)

        reached: Set[str] = set()
        stack = list(roots)
        while stack:
            key = stack.pop()
            if key in reached:
                continue
            reached.add(key)
            for edge in graph.callees(key):
                if edge.callee.key not in reached:
                    stack.append(edge.callee.key)
            fn = graph.functions.get(key)
            lf = summ.locals.get(key)
            if fn is None or lf is None:
                continue
            # by-value references: callbacks handed around, getattr'd
            # names, nested defs passed to executors
            for ref in lf.name_refs:
                for target in project.resolve_ref(
                    fn.module, fn.class_name, ref
                ):
                    if target.key not in reached:
                        stack.append(target.key)
            # lexical-scope references: the call graph skips nested
            # def/lambda bodies and keys nested functions ambiguously,
            # so any same-module function whose bare name the subtree
            # mentions (incl. from closures) counts as live
            mentioned = _subtree_names(fn)
            for other in fn.module.functions.values():
                if (
                    other.key not in reached
                    and other.node.name in mentioned
                ):
                    stack.append(other.key)

        for fn in project.functions():
            if fn.key in reached:
                continue
            bare = fn.node.name
            if not bare.startswith("_") or (
                bare.startswith("__") and bare.endswith("__")
            ):
                continue
            yield Finding(
                "BTL007", fn.module.path, fn.node.lineno,
                fn.node.col_offset,
                f"`{fn.qualname}()` is reachable from no entry point "
                f"(no route, scheduled callback, thread dispatch, or "
                f"call/reference from live code): dead handler or "
                f"orphaned helper — delete it, or keep it deliberately "
                f"with '# batonlint: allow[BTL007]'",
            )
