"""BTL033 — alert rule metric selectors must reference declared metrics.

BTL030 closes the producer half of the "typo forks the series" failure
mode; this closes the consumer half: an alert rule whose ``metric``
selector misspells a name (``timer:loop_lags_s:p95``) parses fine,
evaluates to "not present this tick" forever, and the alert silently
never fires — the exact failure ``checkers/counters.py`` was written
about. Any dict literal that *looks like* an alert rule (string
``name`` + string ``metric`` plus at least one other rule key) has its
selector audited against the same AST-parsed ``DECLARED_*`` registry:

- ``counter:<n>`` — ``n`` in ``DECLARED_COUNTERS`` or extending a
  ``DECLARED_COUNTER_PREFIXES`` family;
- ``gauge:<n>`` — ``n`` in ``DECLARED_GAUGES``;
- ``timer:<t>:<stat>`` — ``t`` in ``DECLARED_TIMERS`` and ``<stat>``
  one of the engine's stat suffixes;
- ``rounds.<series>`` — one of the derived series the engine computes
  from the ``rounds.jsonl`` tail (structural, not registry-backed).

Anything else is a finding. Legacy 2-tuple registry fixtures carry no
timer/gauge sets; those address forms are skipped there, matching
BTL030's degradation.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

#: timer stat suffixes the engine resolves (obs/alerts.py TIMER_STATS)
_TIMER_STATS = frozenset({"count", "mean", "p50", "p95", "p99", "max"})

#: rounds.* series derived from the rounds.jsonl tail
#: (obs/alerts.py::derive_rounds_tail)
_ROUNDS_SERIES = frozenset({
    "tail",
    "straggler_rate",
    "duration_p95",
    "duration_p95_ratio",
    "recompile_storm_rounds",
    "mfu_mean",
    "mfu_ratio",
})

#: keys (beyond name/metric) that mark a dict literal as an alert rule
_RULE_MARKERS = frozenset({
    "op", "threshold", "burn_rate", "for_s", "cooldown_s", "severity",
    "capture", "clear_ratio",
})


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@register
class AlertRuleMetricChecker(Checker):
    rule = "BTL033"
    title = "alert rule selects a metric absent from the DECLARED_* registry"

    def applies_to(self, ctx: CheckContext) -> bool:
        # rule packs can live anywhere (obs/ default pack, tests,
        # operator configs) — audit every module once a registry exists
        return ctx.counter_registry is not None

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Dict):
                continue
            keys = {}
            for k, v in zip(node.keys, node.values):
                name = _const_str(k)
                if name is not None:
                    keys[name] = v
            if "name" not in keys or "metric" not in keys:
                continue
            if not (_RULE_MARKERS & set(keys)):
                continue  # not an alert rule shape (e.g. SLO assertion)
            metric = _const_str(keys["metric"])
            if metric is None:
                continue  # dynamic selector; nothing checkable
            problem = self._audit(metric, ctx.counter_registry)
            if problem:
                rule_name = _const_str(keys["name"]) or "?"
                findings.append(Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"alert rule `{rule_name}`: {problem} — the rule "
                    f"would silently never fire; fix the selector or "
                    f"declare the metric in baton_tpu/utils/metrics.py",
                ))
        return findings

    def _audit(self, metric: str, reg) -> Optional[str]:
        """None when the selector resolves; else the problem text."""
        if metric.startswith("counter:"):
            name = metric[len("counter:"):]
            if name in reg["counters"] or any(
                name.startswith(p) for p in reg["counter_prefixes"]
            ):
                return None
            return (f"counter `{name}` is not declared in "
                    f"DECLARED_COUNTERS / DECLARED_COUNTER_PREFIXES")
        if metric.startswith("gauge:"):
            if reg["gauges"] is None:
                return None  # legacy fixture registry: no gauge audit
            name = metric[len("gauge:"):]
            if name in reg["gauges"]:
                return None
            return f"gauge `{name}` is not declared in DECLARED_GAUGES"
        if metric.startswith("timer:"):
            parts = metric.split(":")
            if len(parts) != 3:
                return (f"timer selector `{metric}` must be "
                        f"`timer:<name>:<stat>`")
            _, name, stat = parts
            if stat not in _TIMER_STATS:
                return (f"timer stat `{stat}` is not one of "
                        f"{sorted(_TIMER_STATS)}")
            if reg["timers"] is None or name in reg["timers"]:
                return None
            return f"timer `{name}` is not declared in DECLARED_TIMERS"
        if metric.startswith("rounds."):
            series = metric[len("rounds."):]
            if series in _ROUNDS_SERIES:
                return None
            return (f"`{metric}` is not a derived rounds series "
                    f"(known: {sorted(_ROUNDS_SERIES)})")
        return (f"selector `{metric}` is not in the evaluable namespace "
                f"(counter:/gauge:/timer:<n>:<stat>/rounds.*)")
