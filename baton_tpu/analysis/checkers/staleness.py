"""BTL003 — stale snapshot of shared state used across an await.

The bug class (ADVICE round 5, the secure-aggregation downgrade): an
async handler snapshots shared mutable state —

    st = self._secure.get(round_name)

— then crosses an ``await`` (body read, ``asyncio.to_thread``, a peer
round-trip).  During that suspension any other handler may run and
re-key the registry (aborted rounds REUSE round names), so the
snapshot now points at a dead object; committing into it or acting on
it afterwards silently diverges from the live state.  The fix pattern
this repo already uses elsewhere (``handle_secure_shares``) is an
identity re-check after the await::

    if self._secure.get(round_name) is not st:
        return web.json_response({"err": "Superseded"}, status=409)

What counts as a *snapshot source* (assignment RHS, walrus included):

* ``self.A[k]`` / ``self.A.get(k)`` — an entry of a shared registry;
* a bare ``self.A`` read where ``A`` is re-assigned by some OTHER
  method of the class (i.e. demonstrably shared-mutable state);
* a same-class/same-module helper call whose return value is, one hop
  down, such a read (``self._secure_state(name)``).

A use of the snapshot *after* a suspension point (an ``await``
expression, or entry into an ``async with`` / ``async for`` header) is
flagged unless a *revalidation* ran in between:

* an ``is``/``is not`` identity comparison of the snapshot against
  anything but ``None``, or a fresh re-read into the same name; or
* **delegated revalidation** — passing the snapshot to a same-class/
  same-module helper that itself compares that parameter (``is`` or
  ``==``) against the snapshot's source attribute (the
  compare-and-invalidate idiom: ``self._invalidate_credentials(cid)``).

A mutation committed into the snapshot in the SAME statement as the
await (``st[...].update(await ...)`` — the pre-fix ``round_start``
shape) is flagged directly: the receiver was read before the
suspension, the write lands after it.

Control flow is **branch-sensitive**: ``if``/``elif``/``else`` arms
are analyzed with forked snapshot states and merged afterwards, and an
arm that *terminates* (ends in ``return``/``raise``/``continue``/
``break``) does not leak its staleness into the fall-through path — so
a guard like ``if cached: return await self._proxy(...)`` no longer
poisons the straight-line code after it, and a re-check that returns
on mismatch validates the surviving path.

Loops are **loop-sensitive**: a ``for``/``while``/``async for`` whose
body suspends is visited twice, the second pass entering with the
state the first pass left — so a snapshot hoisted ABOVE the loop is
correctly stale on every iteration after the first, even when each
single iteration reads the name before its own await.  Findings from
the repass carry loop-carried wording.  ``try`` bodies still visit
sequentially (effects union), so genuinely-safe hits there may need a
justified ``# batonlint: allow[BTL003]``.

Scope: ``async def``s under ``server/`` only.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_TERMINATORS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.A`` -> ``A`` (one level only)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _shared_read_source(
    node: ast.AST,
    mutable_attrs: Set[str],
    helper_sources: Dict[str, str],
    class_name: Optional[str],
) -> Optional[str]:
    """Description of the shared state ``node`` reads, or None.

    Returns e.g. ``"self._secure"`` for ``self._secure.get(k)`` /
    ``self._secure[k]``, ``"self._pending"`` for a bare mutable-attr
    read, or the helper's own source for a one-hop helper call.
    """
    # self.A[k]
    if isinstance(node, ast.Subscript):
        attr = _self_attr(node.value)
        if attr is not None:
            return f"self.{attr}"
        return None
    # self.A.get(k)
    if isinstance(node, ast.Call):
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "get"
        ):
            attr = _self_attr(func.value)
            if attr is not None:
                return f"self.{attr}"
        # one-hop helper: self.helper(...) / helper(...)
        qual = au.resolve_local_call(node, class_name)
        if qual is not None and qual in helper_sources:
            return helper_sources[qual]
        return None
    # bare self.A, only when A is provably shared-mutable
    attr = _self_attr(node)
    if attr is not None and attr in mutable_attrs:
        return f"self.{attr}"
    return None


def _collect_mutable_attrs(tree: ast.Module) -> Dict[Optional[str], Set[str]]:
    """Per class: attrs assigned through ``self`` in a method other
    than ``__init__`` — i.e. state that mutates over the object's
    lifetime, not just construction-time wiring."""
    out: Dict[Optional[str], Set[str]] = {}
    for qual, cls, fn in au.iter_function_defs(tree):
        if cls is None or fn.name == "__init__":
            continue
        for node in au.walk_shallow(fn):
            targets: list = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.setdefault(cls, set()).add(attr)
    return out


def _collect_helper_sources(
    tree: ast.Module, mutable_attrs: Dict[Optional[str], Set[str]]
) -> Dict[str, str]:
    """Qualnames of functions whose return value is (one hop) a shared
    read — e.g. ``_secure_state`` returning ``self._secure.get(name)``
    possibly via a local temp."""
    sources: Dict[str, str] = {}
    for qual, cls, fn in au.iter_function_defs(tree):
        attrs = mutable_attrs.get(cls, set())
        local_src: Dict[str, str] = {}
        returns_src: Optional[str] = None
        for node in au.walk_shallow(fn):
            src = None
            if isinstance(node, ast.Assign):
                src = _shared_read_source(node.value, attrs, {}, cls)
                if src is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_src[t.id] = src
            elif isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                src = _shared_read_source(v, attrs, {}, cls)
                if src is None and isinstance(v, ast.Name):
                    src = local_src.get(v.id)
                if src is None and isinstance(v, ast.IfExp):
                    for arm in (v.body, v.orelse):
                        src = _shared_read_source(arm, attrs, {}, cls) or (
                            local_src.get(arm.id)
                            if isinstance(arm, ast.Name) else None
                        )
                        if src:
                            break
                if src is not None:
                    returns_src = src
        if returns_src is not None:
            sources[qual] = returns_src
    return sources


def _collect_revalidators(
    tree: ast.Module,
) -> Dict[Tuple[str, int], Set[str]]:
    """``(helper_qualname, param_index) -> {snapshot sources}`` for
    helpers that compare one of their parameters (``is``/``is not`` or
    ``==``/``!=``) against a ``self.X`` read — the compare-and-
    invalidate idiom.  A caller passing a snapshot of ``self.X`` into
    such a parameter has delegated the freshness re-check."""
    out: Dict[Tuple[str, int], Set[str]] = {}
    for qual, cls, fn in au.iter_function_defs(tree):
        params = [
            a.arg
            for a in (
                list(getattr(fn.args, "posonlyargs", []))
                + list(fn.args.args)
            )
        ]
        if not params:
            continue
        index = {name: i for i, name in enumerate(params)}
        for node in au.walk_shallow(fn):
            if not isinstance(node, ast.Compare):
                continue
            if not all(
                isinstance(op, (ast.Is, ast.IsNot, ast.Eq, ast.NotEq))
                for op in node.ops
            ):
                continue
            operands = [node.left] + list(node.comparators)
            attrs = set()
            for o in operands:
                # a bare `self.X`, or the registry-read shape
                # `self.X.get(...)` (comparing the snapshot against a
                # FRESH read of the same registry)
                a = _self_attr(o)
                if a is None and isinstance(o, ast.Call):
                    func = o.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr == "get"
                    ):
                        a = _self_attr(func.value)
                if a is not None:
                    attrs.add(f"self.{a}")
            if not attrs:
                continue
            for o in operands:
                if isinstance(o, ast.Name) and o.id in index:
                    out.setdefault(
                        (qual, index[o.id]), set()
                    ).update(attrs)
    return out


class _Tracked:
    __slots__ = ("source", "line", "pending_since", "dead")

    def __init__(self, source: str, line: int) -> None:
        self.source = source          # e.g. "self._secure"
        self.line = line              # snapshot line
        self.pending_since: Optional[int] = None  # line of staling await
        self.dead = False             # already reported / reassigned

    def clone(self) -> "_Tracked":
        tr = _Tracked(self.source, self.line)
        tr.pending_since = self.pending_since
        tr.dead = self.dead
        return tr


def _terminates(block: List[ast.stmt]) -> bool:
    """The block can never fall through to the statement after its
    ``if``: its last statement returns/raises/continues/breaks."""
    return bool(block) and isinstance(block[-1], _TERMINATORS)


def _block_suspends(stmts: List[ast.stmt]) -> bool:
    """The block contains a suspension point outside nested defs."""
    todo: List[ast.AST] = list(stmts)
    while todo:
        n = todo.pop()
        if isinstance(n, _FUNCS):
            continue
        if isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return True
        todo.extend(ast.iter_child_nodes(n))
    return False


@register
class StaleSnapshotChecker(Checker):
    rule = "BTL003"
    title = "shared-state snapshot used across an await without re-check"

    def applies_to(self, ctx: CheckContext) -> bool:
        return "server" in ctx.parts

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        mutable_attrs = _collect_mutable_attrs(ctx.tree)
        helper_sources = _collect_helper_sources(ctx.tree, mutable_attrs)
        revalidators = _collect_revalidators(ctx.tree)
        for qual, cls, fn in au.iter_function_defs(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            self._check_function(
                fn, cls, mutable_attrs.get(cls, set()),
                helper_sources, revalidators, findings, ctx,
            )
        return findings

    # ------------------------------------------------------------------
    def _check_function(
        self, fn, cls, attrs, helper_sources, revalidators, findings, ctx
    ) -> None:

        loop_repass = [0]
        flagged_sites: Set[Tuple[int, int, str]] = set()

        def flag(name: str, tr: _Tracked, node: ast.AST) -> None:
            tr.dead = True
            site = (node.lineno, node.col_offset, name)
            if site in flagged_sites:
                return  # already reported on an earlier loop pass
            flagged_sites.add(site)
            carried = (
                " (loop-carried: the snapshot is taken once but the "
                "loop body suspends, so every iteration after the "
                "first acts on a stale value)"
                if loop_repass[0] else ""
            )
            findings.append(
                Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"`{name}` snapshots `{tr.source}` (line {tr.line}) "
                    f"and is used here after the await on line "
                    f"{tr.pending_since}: the registry may have been "
                    f"re-keyed during the suspension — re-read it or "
                    f"identity-check (`{tr.source} ... is {name}`) "
                    f"before trusting the snapshot" + carried,
                    also_lines=tuple(
                        x for x in (tr.line, tr.pending_since)
                        if x is not None
                    ),
                )
            )

        def flag_same_stmt(name: str, tr: _Tracked, node: ast.AST) -> None:
            tr.dead = True
            site = (node.lineno, node.col_offset, name)
            if site in flagged_sites:
                return
            flagged_sites.add(site)
            findings.append(
                Finding(
                    self.rule, ctx.path, node.lineno, node.col_offset,
                    f"`{name}` snapshots `{tr.source}` (line {tr.line}) "
                    f"and is mutated with the result of an await in the "
                    f"same statement: the receiver was read before the "
                    f"suspension, so the write can land in a dead object "
                    f"if the registry was re-keyed — await into a local, "
                    f"identity-check the snapshot, then commit",
                    also_lines=(tr.line,),
                )
            )

        def exprs_of(stmt) -> List[ast.AST]:
            """Header expressions of a statement (not child statements,
            not nested function bodies)."""
            if isinstance(stmt, (ast.If, ast.While)):
                return [stmt.test]
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                return [stmt.target, stmt.iter]
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                return [i.context_expr for i in stmt.items]
            if isinstance(stmt, ast.Try):
                return []
            if isinstance(stmt, _FUNCS + (ast.ClassDef,)):
                return []
            return [stmt]

        def walk_expr(e) -> Iterable[ast.AST]:
            todo = [e]
            while todo:
                n = todo.pop()
                yield n
                if not isinstance(n, _FUNCS):
                    todo.extend(ast.iter_child_nodes(n))

        def revalidated_names(nodes: List[ast.AST]) -> Set[str]:
            """Names identity-compared (is/is not) against a non-None
            operand anywhere in these expressions."""
            out: Set[str] = set()
            for e in nodes:
                for n in walk_expr(e):
                    if not isinstance(n, ast.Compare):
                        continue
                    if not all(
                        isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
                    ):
                        continue
                    operands = [n.left] + list(n.comparators)
                    non_none = [
                        o for o in operands
                        if not (
                            isinstance(o, ast.Constant) and o.value is None
                        )
                    ]
                    if len(non_none) < 2:
                        continue  # `x is None` checks emptiness, not age
                    for o in operands:
                        if isinstance(o, ast.Name):
                            out.add(o.id)
            return out

        def delegated_revalidations(
            nodes: List[ast.AST],
        ) -> Dict[str, Set[str]]:
            """``{name: {sources}}`` for snapshot names passed into a
            helper parameter that the helper compares against that
            source — the call IS the re-check."""
            out: Dict[str, Set[str]] = {}
            for e in nodes:
                for n in walk_expr(e):
                    if not isinstance(n, ast.Call):
                        continue
                    qual = au.resolve_local_call(n, cls)
                    if qual is None:
                        continue
                    # self.helper(a) binds a to the param AFTER self
                    offset = (
                        1 if isinstance(n.func, ast.Attribute) else 0
                    )
                    for i, arg in enumerate(n.args):
                        if not isinstance(arg, ast.Name):
                            continue
                        sources = revalidators.get((qual, i + offset))
                        if sources:
                            out.setdefault(arg.id, set()).update(sources)
            return out

        def compare_nodes(nodes: List[ast.AST]) -> List[ast.AST]:
            comps = []
            for e in nodes:
                for n in walk_expr(e):
                    if isinstance(n, ast.Compare):
                        comps.append(n)
            return comps

        def uses_of(name: str, nodes: List[ast.AST]) -> List[ast.AST]:
            """Load-context occurrences of ``name`` outside identity
            compares (the compare IS the revalidation, not a use)."""
            comps = compare_nodes(nodes)
            in_comp = {
                id(n) for c in comps for n in ast.walk(c)
            }
            hits = []
            for e in nodes:
                for n in walk_expr(e):
                    if (
                        isinstance(n, ast.Name)
                        and n.id == name
                        and id(n) not in in_comp
                        and isinstance(n.ctx, ast.Load)
                    ):
                        hits.append(n)
            return hits

        def has_await(nodes: List[ast.AST]) -> Optional[ast.AST]:
            for e in nodes:
                for n in walk_expr(e):
                    if isinstance(n, ast.Await):
                        return n
            return None

        def receiver_root(expr) -> Optional[str]:
            root = expr
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            return root.id if isinstance(root, ast.Name) else None

        def same_stmt_commit(stmt, tracked) -> Optional[Tuple[str, ast.AST]]:
            """``st[...].xxx(await ...)`` / ``st[...] = await ...``:
            snapshot receiver mutated with an awaited value."""
            for e in exprs_of(stmt):
                for n in walk_expr(e):
                    if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute
                    ):
                        root = receiver_root(n.func.value)
                        if root in tracked and any(
                            isinstance(x, ast.Await)
                            for a in (n.args + [k.value for k in n.keywords])
                            for x in walk_expr(a)
                        ):
                            return root, n
            if isinstance(stmt, ast.Assign) and any(
                isinstance(x, ast.Await) for x in walk_expr(stmt.value)
            ):
                for t in stmt.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        root = receiver_root(t)
                        if root in tracked:
                            return root, stmt
            return None

        def snapshot_bindings(stmt) -> List[Tuple[str, str, int]]:
            """``(name, source, line)`` for snapshot assignments in the
            statement — plain assigns and walrus expressions."""
            out = []
            if isinstance(stmt, ast.Assign):
                src = _shared_read_source(
                    stmt.value, attrs, helper_sources, cls
                )
                if src is not None:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            out.append((t.id, src, stmt.lineno))
            for e in exprs_of(stmt):
                for n in walk_expr(e):
                    if isinstance(n, ast.NamedExpr) and isinstance(
                        n.target, ast.Name
                    ):
                        src = _shared_read_source(
                            n.value, attrs, helper_sources, cls
                        )
                        if src is not None:
                            out.append((n.target.id, src, n.lineno))
            return out

        def assigned_names(stmt) -> Set[str]:
            out: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(stmt.target, ast.Name):
                    out.add(stmt.target.id)
            return out

        def merge(
            arm_states: List[Tuple[Dict[str, _Tracked], bool]],
        ) -> Dict[str, _Tracked]:
            """Join the snapshot states of the arms that can fall
            through; a terminating arm contributes nothing (its
            staleness dies with it)."""
            live = [st for st, ends in arm_states if not ends]
            if not live:
                return {}
            names = set(live[0])
            for st in live[1:]:
                names &= set(st)
            out: Dict[str, _Tracked] = {}
            for name in names:
                trs = [st[name] for st in live]
                if len({tr.source for tr in trs}) != 1:
                    continue
                m = trs[0].clone()
                for tr in trs[1:]:
                    if tr.dead:
                        m.dead = True
                    if m.pending_since is None:
                        m.pending_since = tr.pending_since
                    m.line = min(m.line, tr.line)
                out[name] = m
            return out

        def visit(stmts, tracked: Dict[str, _Tracked]) -> None:
            for stmt in stmts:
                if isinstance(stmt, _FUNCS + (ast.ClassDef,)):
                    continue
                header = exprs_of(stmt)

                # 1. stale uses (statement-order approximation: the
                #    header of this statement evaluates before any
                #    await IN it suspends, so check uses first).  An
                #    identity re-check in an `if` guard whose arm
                #    terminates (`if self._round is not r: return ...`)
                #    is the full fix idiom — the author installed the
                #    protocol, so STOP tracking the snapshot; a bare
                #    compare merely resets the pending await.
                delegated = delegated_revalidations(header)
                reval = revalidated_names(header)
                guard_installed = isinstance(stmt, ast.If) and (
                    _terminates(stmt.body) or _terminates(stmt.orelse)
                )
                validated: List[str] = []
                for name, tr in list(tracked.items()):
                    if name in reval and guard_installed:
                        validated.append(name)
                        continue
                    if tr.dead or tr.pending_since is None:
                        continue
                    if name in reval:
                        tr.pending_since = None
                        continue
                    if tr.source in delegated.get(name, ()):
                        tr.pending_since = None  # helper does the check
                        continue
                    hits = uses_of(name, header)
                    if hits:
                        flag(name, tr, hits[0])
                for name in validated:
                    tracked.pop(name, None)

                # 2. same-statement commit-through-await pattern
                commit = same_stmt_commit(stmt, tracked)
                if commit is not None:
                    name, node = commit
                    tr = tracked[name]
                    if not tr.dead:
                        flag_same_stmt(name, tr, node)

                # 3. a suspension in this statement stales every
                #    snapshot: an await expression, or entering an
                #    async-with/async-for header (their __aenter__ /
                #    __anext__ suspend too)
                aw = has_await(header)
                line: Optional[int] = aw.lineno if aw is not None else None
                if line is None and isinstance(
                    stmt, (ast.AsyncWith, ast.AsyncFor)
                ):
                    line = stmt.lineno
                if line is not None:
                    for tr in tracked.values():
                        if not tr.dead and tr.pending_since is None:
                            tr.pending_since = line

                # 4. (re)bindings: fresh snapshots reset, anything else
                #    stops tracking the name
                fresh = snapshot_bindings(stmt)
                for name, src, sline in fresh:
                    tracked[name] = _Tracked(src, sline)
                for name in assigned_names(stmt) - {
                    n for n, _s, _l in fresh
                }:
                    tracked.pop(name, None)

                # 5. child blocks: `if` arms fork and merge (branch-
                #    sensitive; a terminating arm's staleness never
                #    reaches the fall-through), everything else visits
                #    sequentially (effects union — conservative)
                if isinstance(stmt, ast.If):
                    arms: List[Tuple[Dict[str, _Tracked], bool]] = []
                    for block in (stmt.body, stmt.orelse):
                        st = {
                            name: tr.clone()
                            for name, tr in tracked.items()
                        }
                        visit(block, st)
                        arms.append((st, _terminates(block)))
                    merged = merge(arms)
                    tracked.clear()
                    tracked.update(merged)
                    continue
                # loops whose body suspends: visit the body a second
                # time with iteration 1's end state — a snapshot hoisted
                # above the loop is fresh on iteration 1 but stale on
                # every later one (the repass marks it pending at the
                # loop header and re-runs the body with loop-carried
                # wording)
                if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    visit(stmt.body, tracked)
                    if isinstance(stmt, ast.AsyncFor) or _block_suspends(
                        stmt.body
                    ):
                        for tr in tracked.values():
                            if not tr.dead and tr.pending_since is None:
                                tr.pending_since = stmt.lineno
                        loop_repass[0] += 1
                        try:
                            visit(stmt.body, tracked)
                        finally:
                            loop_repass[0] -= 1
                    visit(stmt.orelse, tracked)
                    continue
                for block in (
                    getattr(stmt, "body", None),
                    getattr(stmt, "orelse", None),
                    getattr(stmt, "finalbody", None),
                ):
                    if isinstance(block, list):
                        visit(block, tracked)
                for handler in getattr(stmt, "handlers", []) or []:
                    visit(handler.body, tracked)

        visit(fn.body, {})
