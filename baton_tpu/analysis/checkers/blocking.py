"""BTL001 — blocking calls reachable from ``async def`` in server/.

One blocking call on the manager's event loop stalls every heartbeat,
blob Range GET, and upload ack at once (the exact failure PR 3's ingest
pipeline exists to prevent). This rule flags calls that synchronously
block — ``time.sleep``, ``pickle.loads``, ``zlib.*``, file I/O,
``.block_until_ready()``, ``jax.device_get`` — when they execute ON the
loop: directly in an ``async def`` body, or inside any plain (sync)
helper the async function reaches through the project call graph.

Reachability comes from the bottom-up fixpoint summaries
(:mod:`baton_tpu.analysis.summaries`): helper chains resolve across
modules and through class-hierarchy dispatch (``self.helper()`` hits
every known override), to any depth, and each finding carries the
witness chain.  The finding points at the blocking call itself — which
may be in a NON-server module when a server handler reaches into a
shared helper — and is additionally suppressible at the async caller's
call site when both live in the same file.

Work routed off the loop is not flagged: nested ``def``/``lambda``
bodies are skipped (they are the closures handed to
``asyncio.to_thread`` / ``run_in_executor`` / the ingest pool), the
routing calls themselves are awaits, and a sync frame merely *calling*
an ``async def`` (no await possible) only builds a coroutine, so
nothing behind it is considered reached.

The rule is execution-context sensitive (third pass): a SYNC function
whose context witness roots it on the event loop through a
registration — a ``PeriodicTask`` callback, ``loop.call_soon`` /
``add_done_callback`` target, or a sync route handler — is held to the
same standard as an ``async def``, while a function dispatched only to
worker threads (``to_thread`` / executor ``submit``) may legally block
and is never flagged.

The blocked-primitive tables live in
:mod:`baton_tpu.analysis.summaries` (the summary extraction records
the sites); this module owns the reachability policy and reporting.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.summaries import (  # noqa: F401  (re-exported)
    BLOCKED_DOTTED,
    BLOCKED_METHODS,
    BLOCKED_MODULE_PREFIXES,
    BLOCKED_NAMES,
    blocked_reason,
    get_summaries,
)

_ROUTE_HINT = (
    "; route it through asyncio.to_thread / run_in_executor / the "
    "ingest pool, or suppress with '# batonlint: allow[BTL001]'"
)


@register
class BlockingCallChecker(ProjectChecker):
    rule = "BTL001"
    title = "blocking call reachable from async def in baton_tpu/server/"

    def check_project(self, project) -> Iterable[Finding]:
        findings: List[Finding] = []
        summaries = get_summaries(project)
        for fn in project.functions():
            if "server" not in fn.module.parts:
                continue
            if not isinstance(fn.node, ast.AsyncFunctionDef):
                continue
            lf = summaries.locals.get(fn.key)
            if lf is not None:
                for line, col, _display, reason in lf.blocking:
                    findings.append(
                        Finding(
                            self.rule, fn.module.path, line, col,
                            f"{reason} (in `async def {fn.node.name}`)"
                            + _ROUTE_HINT,
                        )
                    )
            # transitive: sync helpers invoked from the async body run
            # on the loop too — the regression vector a direct-only
            # check misses (report_update -> _persist_pending -> disk).
            # Only SYNC callees: an async callee is an async def in its
            # own right and gets its own direct findings.
            for edge in summaries.graph.callees(fn.key):
                callee = summaries.get(edge.callee.key)
                if callee is None or callee.is_async:
                    continue
                for (path, line, col), (
                    _display, reason, chain,
                ) in sorted(callee.blocking.items()):
                    full_chain = (edge.callee.qualname,) + chain
                    via = " -> ".join(f"{q}()" for q in full_chain)
                    also = (
                        (edge.node.lineno,)
                        if path == fn.module.path else ()
                    )
                    findings.append(
                        Finding(
                            self.rule, path, line, col,
                            f"{reason} (reached from `async def "
                            f"{fn.node.name}` via {via})" + _ROUTE_HINT,
                            also_lines=also,
                        )
                    )
        # context pass: sync functions the entry-point model roots on
        # the event loop through a REGISTRATION (PeriodicTask, loop
        # callbacks, sync route handlers) block the loop exactly like
        # an async def body; thread-only functions legally block and
        # are exempt by construction (no loop witness).
        seen_sites = {(f.path, f.line, f.col) for f in findings}
        for fn in project.functions():
            if isinstance(fn.node, ast.AsyncFunctionDef):
                continue
            lf = summaries.locals.get(fn.key)
            if lf is None or not lf.blocking:
                continue
            w = summaries.witness(fn.key, "loop")
            if w is None or w.seed == "async" or not w.server:
                continue
            hop = (
                " -> ".join(f"{q}()" for q in (w.root_qual,) + w.chain)
                if w.chain else f"{w.root_qual}()"
            )
            also = (
                (w.reg_line,) if w.reg_path == fn.module.path else ()
            )
            for line, col, _display, reason in lf.blocking:
                if (fn.module.path, line, col) in seen_sites:
                    continue
                findings.append(
                    Finding(
                        self.rule, fn.module.path, line, col,
                        f"{reason} (in `{fn.qualname}`, which runs on "
                        f"the event loop: {hop} {w.reason})"
                        + _ROUTE_HINT,
                        also_lines=also,
                    )
                )
        return findings
