"""BTL001 — blocking calls reachable from ``async def`` in server/.

One blocking call on the manager's event loop stalls every heartbeat,
blob Range GET, and upload ack at once (the exact failure PR 3's ingest
pipeline exists to prevent). This rule flags calls that synchronously
block — ``time.sleep``, ``pickle.loads``, ``zlib.*``, file I/O,
``.block_until_ready()``, ``jax.device_get`` — when they execute ON the
loop: directly in an ``async def`` body, or inside a plain helper the
async function calls (resolved transitively through same-module
``self.helper()`` / ``helper()`` calls).

Work routed off the loop is not flagged: nested ``def``/``lambda``
bodies are skipped (they are the closures handed to
``asyncio.to_thread`` / ``run_in_executor`` / the ingest pool), and the
routing calls themselves are awaits, not blocking calls.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

# fully-resolved dotted names that block the loop
BLOCKED_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop; await asyncio.sleep",
    "pickle.load": "pickle.load() is blocking CPU/IO work",
    "pickle.loads": "pickle.loads() is blocking CPU work",
    "jax.device_get": "jax.device_get() blocks on device transfer",
}
# any call into these modules blocks (compression is pure CPU burn)
BLOCKED_MODULE_PREFIXES = ("zlib.",)
# bare-name builtins
BLOCKED_NAMES = {"open": "open() is blocking file I/O"}
# method attributes that block regardless of receiver type
BLOCKED_METHODS = {
    "block_until_ready": ".block_until_ready() blocks on device compute",
    "read_text": "file I/O (.read_text) blocks the event loop",
    "write_text": "file I/O (.write_text) blocks the event loop",
    "read_bytes": "file I/O (.read_bytes) blocks the event loop",
    "write_bytes": "file I/O (.write_bytes) blocks the event loop",
}

_ROUTE_HINT = (
    "; route it through asyncio.to_thread / run_in_executor / the "
    "ingest pool, or suppress with '# batonlint: allow[BTL001]'"
)


def _blocked_reason(call: ast.Call) -> Optional[Tuple[str, str]]:
    """``(display_name, reason)`` when the call is a blocking
    primitive, else None."""
    name = au.call_name(call)
    if name is not None:
        if name in BLOCKED_DOTTED:
            return name, BLOCKED_DOTTED[name]
        for prefix in BLOCKED_MODULE_PREFIXES:
            if name.startswith(prefix):
                return name, f"{prefix}* compression is blocking CPU work"
        if name in BLOCKED_NAMES:
            return name, BLOCKED_NAMES[name]
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in BLOCKED_METHODS:
        display = name if name is not None else f"<expr>.{func.attr}"
        return display, BLOCKED_METHODS[func.attr]
    return None


@register
class BlockingCallChecker(Checker):
    rule = "BTL001"
    title = "blocking call reachable from async def in baton_tpu/server/"

    def applies_to(self, ctx: CheckContext) -> bool:
        return "server" in ctx.parts

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        sync_index = au.sync_function_index(ctx.tree)
        findings: List[Finding] = []
        # memoized per-helper scan: [(call_node, display, reason)]
        helper_hits: Dict[str, list] = {}

        def scan_direct(node) -> list:
            hits = []
            for child in au.walk_shallow(node):
                if isinstance(child, ast.Call):
                    blocked = _blocked_reason(child)
                    if blocked is not None:
                        hits.append((child, *blocked))
            return hits

        def helper_chain_hits(qual: str, visited: frozenset) -> list:
            """Blocking hits in ``qual`` and the sync helpers it calls."""
            if qual in visited:
                return []
            if qual in helper_hits:
                return helper_hits[qual]
            node = sync_index.get(qual)
            if node is None:
                return []
            hits = list(scan_direct(node))
            cls = qual.rsplit(".", 1)[0] if "." in qual else None
            for child in au.walk_shallow(node):
                if isinstance(child, ast.Call):
                    callee = au.resolve_local_call(child, cls)
                    if callee is not None and callee != qual:
                        for hit in helper_chain_hits(
                            callee, visited | {qual}
                        ):
                            hits.append(hit)
            helper_hits[qual] = hits
            return hits

        for qual, cls, node in au.iter_function_defs(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for call, display, reason in scan_direct(node):
                findings.append(
                    Finding(
                        self.rule, ctx.path, call.lineno, call.col_offset,
                        f"{reason} (in `async def {node.name}`)"
                        + _ROUTE_HINT,
                    )
                )
            # transitive: sync helpers invoked from the async body run
            # on the loop too — the regression vector a direct-only
            # check misses (report_update -> _persist_pending -> disk)
            for child in au.walk_shallow(node):
                if not isinstance(child, ast.Call):
                    continue
                callee = au.resolve_local_call(child, cls)
                if callee is None or callee not in sync_index:
                    continue
                for call, display, reason in helper_chain_hits(
                    callee, frozenset()
                ):
                    findings.append(
                        Finding(
                            self.rule, ctx.path,
                            call.lineno, call.col_offset,
                            f"{reason} (reached from `async def "
                            f"{node.name}` via {callee}())" + _ROUTE_HINT,
                            also_lines=(child.lineno,),
                        )
                    )
        return findings
