"""BTL011 — undeclared buffer-donation policy on jitted state steppers.

A ``jax.jit``'d round-step/training function that takes model-state
pytrees (``params``, optimizer state, per-client anchors...) holds TWO
copies of that state live across the dispatch unless the input buffers
are donated — on real accelerators that is the difference between
fitting the flagship stage in HBM and not. Donation is also *unsafe*
exactly when the caller reuses the arrays after the call (the engine's
per-round paths retain the anchor copy for the next wave), so the
policy can't be a blanket default: it must be DECIDED per jit site.

The rule therefore flags any jit application whose target function has
a parameter named like federated model state

    params, anchors, cluster_params, personal_state,
    opt_states, opt_state, server_opt_state

when the jit call/decorator carries no ``donate_argnums`` /
``donate_argnames`` keyword. Passing an explicit ``donate_argnums=()``
records "considered, and the answer is no" and satisfies the rule; so
does a ``# batonlint: allow[BTL011]`` comment with a justification at
the jit site (or at the target's ``def`` line).

Recognized jit applications:

* decorators — ``@jax.jit``, ``@jit``, ``@jax.jit(...)``,
  ``@partial(jax.jit, ...)``;
* call sites — ``jax.jit(fn, ...)`` where ``fn`` is a same-module
  ``def``, a lambda, a ``shard_map(kernel, ...)`` expression, or a
  local name previously bound to one (the engine's
  ``sharded = shard_map(kernel, ...); jax.jit(sharded)`` shape).

``self``/``cls`` are ignored (static under ``static_argnums``), and
functions whose parameters carry none of the state names are out of
scope — donation of activations/data is a per-kernel judgement call,
not a policy this rule can audit.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.engine import Checker, CheckContext, Finding, register

# parameter names that mean "a model-state pytree rides this argument"
_STATE_PARAMS = frozenset({
    "params",
    "anchors",
    "cluster_params",
    "personal_state",
    "opt_states",
    "opt_state",
    "server_opt_state",
})

_DONATE_KEYWORDS = {"donate_argnums", "donate_argnames"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_jit(node: ast.AST) -> bool:
    name = au.dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "jit"


def _is_shard_map(node: ast.AST) -> bool:
    name = au.dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "shard_map"


def _has_donate_decision(call: Optional[ast.Call]) -> bool:
    """True when the jit application names a donation policy — ANY
    ``donate_argnums``/``donate_argnames`` keyword counts, including an
    explicit empty tuple (an audited "no")."""
    if call is None:
        return False
    return any(
        kw.arg in _DONATE_KEYWORDS for kw in call.keywords if kw.arg
    )


@register
class DonationPolicyChecker(Checker):
    rule = "BTL011"
    title = "jitted state-stepping function with no donation decision"

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        findings: List[Finding] = []

        defs_by_name: Dict[str, ast.AST] = {}
        for _qual, _cls, node in au.iter_function_defs(ctx.tree):
            defs_by_name.setdefault(node.name, node)

        # local names bound to shard_map(...) results:
        # sharded = shard_map(kernel, ...); later jax.jit(sharded)
        shardmap_bindings: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target, value = node.targets[0], node.value
            if not (isinstance(target, ast.Name)
                    and isinstance(value, ast.Call)
                    and _is_shard_map(value.func)):
                continue
            fn = self._resolve_target(value.args[0] if value.args else None,
                                      defs_by_name, {})
            if fn is not None:
                shardmap_bindings[target.id] = fn

        seen = set()

        def audit(fn: Optional[ast.AST], site: ast.AST,
                  jit_call: Optional[ast.Call]) -> None:
            if fn is None or (id(fn), site.lineno) in seen:
                return
            seen.add((id(fn), site.lineno))
            if _has_donate_decision(jit_call):
                return
            state_args = sorted(
                (au.param_names(fn) - {"self", "cls"}) & _STATE_PARAMS
            )
            if not state_args:
                return
            label = getattr(fn, "name", "<lambda>")
            findings.append(Finding(
                self.rule, ctx.path, site.lineno, site.col_offset,
                f"jax.jit on `{label}` takes model-state pytrees "
                f"({', '.join(state_args)}) with no donation decision; "
                f"pass donate_argnums (an explicit `()` records an "
                f"audited no) or justify with # batonlint: allow[BTL011]",
                also_lines=(fn.lineno,) if fn.lineno != site.lineno else (),
            ))

        # decorator applications
        for _qual, _cls, node in au.iter_function_defs(ctx.tree):
            for dec in node.decorator_list:
                jit_call = None
                if _is_jit(dec):
                    pass  # bare @jax.jit — no keywords possible
                elif isinstance(dec, ast.Call) and _is_jit(dec.func):
                    jit_call = dec  # @jax.jit(...) factory
                elif (
                    isinstance(dec, ast.Call)
                    and (au.dotted_name(dec.func) or "").rsplit(".", 1)[-1]
                    == "partial"
                    and dec.args
                    and _is_jit(dec.args[0])
                ):
                    jit_call = dec  # @partial(jax.jit, ...)
                else:
                    continue
                audit(node, dec, jit_call)

        # call-site applications
        for call in ast.walk(ctx.tree):
            if not (isinstance(call, ast.Call) and call.args
                    and _is_jit(call.func)):
                continue
            fn = self._resolve_target(call.args[0], defs_by_name,
                                      shardmap_bindings)
            audit(fn, call, call)

        return findings

    @staticmethod
    def _resolve_target(
        target: Optional[ast.AST],
        defs_by_name: Dict[str, ast.AST],
        shardmap_bindings: Dict[str, ast.AST],
    ) -> Optional[ast.AST]:
        """The function a jit/shard_map application traces, when it is
        statically visible in this module; None for dynamic targets
        (call results, attributes) — those are out of scope."""
        if target is None:
            return None
        if isinstance(target, ast.Lambda):
            return target
        if isinstance(target, ast.Name):
            return defs_by_name.get(target.id) or shardmap_bindings.get(
                target.id
            )
        if isinstance(target, ast.Call) and _is_shard_map(target.func):
            return DonationPolicyChecker._resolve_target(
                target.args[0] if target.args else None,
                defs_by_name, shardmap_bindings,
            )
        return None
