"""BTL000 — stale ``# batonlint: allow[...]`` suppression.

A suppression that silences nothing is a finding in its own right: it
documents a hazard that no longer exists (or never did), and — worse —
it will silently absorb the NEXT real instance of that rule introduced
on its line.  Every rule upgrade that fixes a false positive should
therefore be paired with deleting the allows it obsoletes; BTL000
enforces that pairing.

The audit itself lives in the engine (:func:`~baton_tpu.analysis.
engine._audit_suppressions`) because it needs the complete
suppression-usage marks from every other checker's pass; this class
only registers the rule id so ``--select BTL000`` and the rule table
work.  A named token is audited only when its rule ran this pass
without crashing, ``allow[*]`` is stale when the line silenced nothing,
and ``allow[BTL000]`` tokens are never audited (no sound way to
self-audit) — which also means a justified-but-currently-quiet allow
can be kept by adding BTL000 to its token list.
"""

from __future__ import annotations

from typing import Iterable

from baton_tpu.analysis.engine import (
    Checker,
    CheckContext,
    Finding,
    register,
)


@register
class StaleSuppressionChecker(Checker):
    rule = "BTL000"
    title = "allow[...] suppression that no longer silences anything"

    def check(self, ctx: CheckContext) -> Iterable[Finding]:
        return ()  # engine-integrated: see _audit_suppressions
