"""BTL005/BTL006 — cross-execution-context concurrency hygiene.

Both rules consume the execution-context lattice from
:mod:`~baton_tpu.analysis.summaries`: every function is rooted at the
entry points that can actually run it (``async def`` bodies, route
registrations, ``PeriodicTask``/loop callbacks -> *loop* context;
``asyncio.to_thread`` / executor ``submit`` / ``run_in_executor`` /
``threading.Thread(target=...)`` -> *thread* context), with context
propagated along execution edges of the call graph.

BTL005: instance or module state written from thread context while any
loop-context function also mutates the same state, with no common
``threading.Lock`` held around both sides (write/write on the same
group is the unambiguous race; bare reference reads are GIL-atomic and
the staleness rules own read-side windows).  Grouping is by LEAF
dotted path (``_round.acc``, not ``_round``): a fold-lane thread
mutating ``r.acc`` does not conflict with loop bookkeeping on
``r.contributors`` — disjoint leaves of the same root object are
independent state.  An ``asyncio.Lock``
explicitly does NOT count — it excludes coroutines from each other but
a worker thread never awaits it.  Constructors are exempt (the object
is not shared yet).  Accesses through stable ``self`` aliases
(``r = self._round`` captured by a fold closure) are attributed to the
underlying attribute, so executor-lane closures are visible.

BTL006: asyncio primitives (``self.X = asyncio.Event()/Queue()/...``)
touched through their non-threadsafe mutation APIs (``.set()``,
``.put_nowait()``, ``.set_result()``, ...) from thread context, and
receiver-agnostic loop-affine calls (``loop.call_soon``,
``create_task``) made from thread context.  The fix is to marshal back
onto the loop: ``loop.call_soon_threadsafe(...)`` /
``asyncio.run_coroutine_threadsafe(...)`` — both of which this rule
recognizes as safe (they are loop-callback *registrations*, not
touches).

Scope: both rules report only inside ``server/`` and ``obs/`` modules —
the runtime tiers where the loop/thread split is real.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis.engine import Finding, ProjectChecker, register
from baton_tpu.analysis.summaries import (
    CtxWitness,
    get_summaries,
    lock_identity,
)

_CTOR_NAMES = {"__init__", "__post_init__"}


def _in_scope(mod) -> bool:
    return any(p in ("server", "obs") for p in mod.parts)


def _witness_desc(w: CtxWitness) -> str:
    """Human chain for a context witness: entry point + path taken."""
    if w.chain:
        via = " -> ".join(f"{q}()" for q in w.chain)
        return f"{w.root_qual}() [{w.reason}] via {via}"
    return f"{w.root_qual}() [{w.reason}]"


def _root_of(project, fn) -> Optional[str]:
    if fn.class_name is None:
        return None
    return (
        project.root_class_name(fn.module, fn.class_name)
        or fn.class_name
    )


def _norm_locks(locks, fn, project) -> frozenset:
    return frozenset(
        x for x in (
            lock_identity(raw, fn.class_name, fn.module, project)
            for raw in locks
        ) if x is not None
    )


@register
class CrossContextStateChecker(ProjectChecker):
    rule = "BTL005"
    title = (
        "state written from thread context while the event loop also "
        "mutates it needs a shared threading.Lock (asyncio.Lock cannot "
        "exclude a thread)"
    )

    def check_project(self, project) -> Iterable[Finding]:
        summ = get_summaries(project)
        # (group_key, attr) -> {"thread_writes": [...], "loop_accesses": [...]}
        state: Dict[Tuple[str, str], Dict[str, list]] = {}

        def bucket(key: Tuple[str, str]) -> Dict[str, list]:
            return state.setdefault(
                key, {"thread_writes": [], "loop_accesses": []}
            )

        for fn in project.functions():
            lf = summ.locals.get(fn.key)
            if lf is None or not _in_scope(fn.module):
                continue
            kinds = summ.context_kinds(fn.key)
            if not kinds or fn.node.name in _CTOR_NAMES:
                continue
            accesses: List[Tuple[Tuple[str, str], int, int, bool,
                                 frozenset]] = []
            root = _root_of(project, fn)
            if root is not None:
                for attr, line, col, is_write, slocks, _al in (
                    lf.attr_accesses
                ):
                    accesses.append((
                        (f"class {root}", attr), line, col, is_write,
                        _norm_locks(slocks, fn, project),
                    ))
            for name, line, col, is_write, slocks in lf.global_accesses:
                accesses.append((
                    (f"module {fn.module.name}", name), line, col,
                    is_write, _norm_locks(slocks, fn, project),
                ))
            for key, line, col, is_write, locks in accesses:
                if not is_write:
                    continue  # write/write only: ref reads are atomic
                b = bucket(key)
                if "thread" in kinds:
                    b["thread_writes"].append((fn, line, col, locks))
                if "loop" in kinds:
                    b["loop_accesses"].append(
                        (fn, line, col, is_write, locks)
                    )

        for (group, attr), b in sorted(
            state.items(), key=lambda kv: kv[0]
        ):
            if not b["thread_writes"] or not b["loop_accesses"]:
                continue
            flagged: set = set()
            for wfn, wline, wcol, wlocks in b["thread_writes"]:
                for lfn, lline, _lcol, _lw, llocks in b["loop_accesses"]:
                    if wlocks & llocks:
                        continue  # both sides hold a common sync lock
                    if wfn.key in flagged:
                        break
                    flagged.add(wfn.key)
                    w = summ.witness(wfn.key, "thread")
                    display = (
                        f"self.{attr}" if group.startswith("class")
                        else attr
                    )
                    also = (
                        (lline,) if lfn.module.path == wfn.module.path
                        else ()
                    )
                    yield Finding(
                        "BTL005", wfn.module.path, wline, wcol,
                        f"`{display}` ({group}) is written here in "
                        f"THREAD context ({_witness_desc(w)}) while "
                        f"`{lfn.qualname}()` mutates it on the event "
                        f"loop with no common threading.Lock held on "
                        f"both sides; an asyncio.Lock does not count — "
                        f"a worker thread never awaits it. Guard both "
                        f"sides with one threading.Lock or confine the "
                        f"write to the loop via "
                        f"loop.call_soon_threadsafe(...)",
                        also_lines=also,
                    )
                    break


@register
class AsyncioFromThreadChecker(ProjectChecker):
    rule = "BTL006"
    title = (
        "asyncio primitive touched from thread context; marshal through "
        "call_soon_threadsafe / run_coroutine_threadsafe"
    )

    def check_project(self, project) -> Iterable[Finding]:
        summ = get_summaries(project)
        # asyncio primitives by (root class, attr), from any method
        prims: set = set()
        for fn in project.functions():
            lf = summ.locals.get(fn.key)
            if lf is None:
                continue
            root = _root_of(project, fn)
            if root is None:
                continue
            for attr in lf.asyncio_defs:
                prims.add((root, attr))

        for fn in project.functions():
            lf = summ.locals.get(fn.key)
            if lf is None or not _in_scope(fn.module):
                continue
            kinds = summ.context_kinds(fn.key)
            if "thread" not in kinds:
                continue
            w = summ.witness(fn.key, "thread")
            root = _root_of(project, fn)
            for attr, line, col, method in lf.asyncio_touches:
                if attr == "<loop>":
                    yield Finding(
                        "BTL006", fn.module.path, line, col,
                        f"`.{method}(...)` is loop-affine but "
                        f"`{fn.qualname}()` runs in THREAD context "
                        f"({_witness_desc(w)}); from a thread use "
                        f"loop.call_soon_threadsafe(...) or "
                        f"asyncio.run_coroutine_threadsafe(...)",
                    )
                elif root is not None and (root, attr) in prims:
                    yield Finding(
                        "BTL006", fn.module.path, line, col,
                        f"`self.{attr}.{method}()` touches an asyncio "
                        f"primitive from THREAD context "
                        f"({_witness_desc(w)}); asyncio primitives are "
                        f"not thread-safe — hand the call to the loop "
                        f"with loop.call_soon_threadsafe"
                        f"(self.{attr}.{method}, ...)",
                    )
