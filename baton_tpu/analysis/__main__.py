"""batonlint CLI: ``python -m baton_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 engine/usage errors — so CI can
fail the build on any finding while distinguishing broken invocations.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import subprocess
import sys
from typing import List, Optional

from baton_tpu.analysis.engine import (
    all_rules,
    apply_baseline,
    finding_fingerprints,
    format_json,
    format_text,
    run_paths,
)


def _load_baseline(path: str) -> Optional[List[str]]:
    """Committed baseline fingerprints: ``{"version": 1,
    "fingerprints": [...]}`` (a bare JSON list is also accepted);
    None on unreadable/malformed input."""
    try:
        data = json.loads(
            pathlib.Path(path).read_text(encoding="utf-8")
        )
    except (OSError, ValueError):
        return None
    if isinstance(data, list):
        return [str(x) for x in data]
    if isinstance(data, dict) and isinstance(
        data.get("fingerprints"), list
    ):
        return [str(x) for x in data["fingerprints"]]
    return None


def _git_changed_files() -> Optional[List[str]]:
    """Python files touched vs HEAD (staged + unstaged + untracked),
    absolute paths; None when git is unavailable — the caller falls
    back to a full lint rather than silently checking nothing."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30,
        )
        if top.returncode != 0:
            return None
        root = pathlib.Path(top.stdout.strip())
        out: List[str] = []
        for cmd in (
            ["git", "diff", "--name-only", "HEAD", "--"],
            ["git", "ls-files", "--others", "--exclude-standard"],
        ):
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=30, cwd=root
            )
            if proc.returncode != 0:
                return None
            out.extend(
                str(root / line)
                for line in proc.stdout.splitlines()
                if line.endswith(".py")
            )
        return sorted(set(out))
    except (OSError, subprocess.SubprocessError):
        return None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m baton_tpu.analysis",
        description=(
            "batonlint: AST invariant checks for event-loop, wire-cap, "
            "lock, JAX-tracer, and metrics hygiene"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["baton_tpu"],
        help="files or directories to lint (default: baton_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select",
        action="append",
        metavar="RULE",
        help="run only these rule ids (repeatable, e.g. --select BTL020)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help=(
            "report findings only in files changed per git (diff vs "
            "HEAD + untracked); the whole project is still loaded so "
            "cross-module rules stay sound"
        ),
    )
    parser.add_argument(
        "--json-out",
        metavar="FILE",
        help="additionally write the JSON report to FILE (CI artifact)",
    )
    parser.add_argument(
        "--sarif",
        metavar="FILE",
        help="additionally write a SARIF 2.1.0 report to FILE",
    )
    parser.add_argument(
        "--baseline",
        metavar="FILE",
        help=(
            "fail only on findings whose fingerprint is absent from "
            "this committed baseline (see --write-baseline); "
            "baselined findings are counted, not printed"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        metavar="FILE",
        help=(
            "write the current findings' fingerprints to FILE and "
            "exit 0 — the debt snapshot --baseline diffs against"
        ),
    )
    parser.add_argument(
        "--cache",
        metavar="FILE",
        nargs="?",
        const=".batonlint_cache.json",
        default=None,
        help=(
            "incremental summary cache keyed by file content hash "
            "(default file when given bare: .batonlint_cache.json); "
            "hit/miss counts appear in the JSON report"
        ),
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, title in all_rules().items():
            print(f"{rule}  {title}")
        return 0

    only_paths = None
    if args.changed_only:
        only_paths = _git_changed_files()
        if only_paths is None:
            print(
                "batonlint: --changed-only: git unavailable, "
                "linting everything",
                file=sys.stderr,
            )

    try:
        report = run_paths(args.paths, rules=args.select,
                           only_paths=only_paths,
                           cache_path=args.cache)
    except KeyError as exc:
        print(f"batonlint: {exc.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        payload = {
            "version": 1,
            "fingerprints": sorted(
                finding_fingerprints(report.findings)
            ),
        }
        try:
            pathlib.Path(args.write_baseline).write_text(
                json.dumps(payload, indent=2) + "\n", encoding="utf-8"
            )
        except OSError as exc:
            print(
                f"batonlint: cannot write {args.write_baseline}: {exc}",
                file=sys.stderr,
            )
            return 2
        print(
            f"batonlint: baseline of {len(report.findings)} "
            f"fingerprint(s) written to {args.write_baseline}"
        )
        return 0

    if args.baseline:
        fingerprints = _load_baseline(args.baseline)
        if fingerprints is None:
            print(
                f"batonlint: unreadable baseline {args.baseline}",
                file=sys.stderr,
            )
            return 2
        apply_baseline(report, fingerprints)

    if args.json_out:
        try:
            pathlib.Path(args.json_out).write_text(
                format_json(report) + "\n", encoding="utf-8"
            )
        except OSError as exc:
            print(f"batonlint: cannot write {args.json_out}: {exc}",
                  file=sys.stderr)
            return 2

    if args.sarif:
        from baton_tpu.analysis.sarif import format_sarif

        try:
            pathlib.Path(args.sarif).write_text(
                format_sarif(report) + "\n", encoding="utf-8"
            )
        except OSError as exc:
            print(f"batonlint: cannot write {args.sarif}: {exc}",
                  file=sys.stderr)
            return 2

    print(format_json(report) if args.format == "json" else format_text(report))
    if report.errors:
        return 2
    return 0 if not report.findings else 1


if __name__ == "__main__":
    sys.exit(main())
