"""batonlint CLI: ``python -m baton_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 engine/usage errors — so CI can
fail the build on any finding while distinguishing broken invocations.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from baton_tpu.analysis.engine import (
    all_rules,
    format_json,
    format_text,
    run_paths,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m baton_tpu.analysis",
        description=(
            "batonlint: AST invariant checks for event-loop, wire-cap, "
            "lock, JAX-tracer, and metrics hygiene"
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["baton_tpu"],
        help="files or directories to lint (default: baton_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select",
        action="append",
        metavar="RULE",
        help="run only these rule ids (repeatable, e.g. --select BTL020)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, title in all_rules().items():
            print(f"{rule}  {title}")
        return 0

    try:
        report = run_paths(args.paths, rules=args.select)
    except KeyError as exc:
        print(f"batonlint: {exc.args[0]}", file=sys.stderr)
        return 2

    print(format_json(report) if args.format == "json" else format_text(report))
    if report.errors:
        return 2
    return 0 if not report.findings else 1


if __name__ == "__main__":
    sys.exit(main())
