"""Static call graph over a batonlint :class:`~.project.Project`.

Edges are the calls :meth:`Project.resolve_call` can pin down
statically — same-module helpers, ``self.method``, imported symbols,
and ``alias.func`` through an imported module.  Each edge keeps its
call-site node so downstream rules (lock-order, staleness) can report
the path a hazard travels, not just its endpoints.

The graph is intentionally an over-approximation in neither direction:
unresolvable calls (dynamic dispatch, HOFs, inheritance) are simply
absent, so rules built on it UNDER-report across those boundaries and
say so in their docs rather than guessing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.project import FunctionInfo, Project

__all__ = ["CallEdge", "CallGraph"]


@dataclasses.dataclass
class CallEdge:
    caller: FunctionInfo
    callee: FunctionInfo
    node: ast.Call                # the call site, in caller's module

    @property
    def line(self) -> int:
        return self.node.lineno


class CallGraph:
    """``caller key -> [CallEdge]``; keys are ``module:Qual.name``."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {
            fn.key: fn for fn in project.functions()
        }
        self.edges: Dict[str, List[CallEdge]] = {}
        for fn in project.functions():
            out: List[CallEdge] = []
            for node in au.walk_shallow(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = project.resolve_call(
                    fn.module, fn.class_name, node
                )
                if callee is not None and callee.key != fn.key:
                    out.append(CallEdge(fn, callee, node))
            self.edges[fn.key] = out

    def callees(self, key: str) -> List[CallEdge]:
        return self.edges.get(key, [])

    def walk_from(
        self, key: str, max_depth: Optional[int] = None
    ) -> Iterator[Tuple[Tuple[str, ...], CallEdge]]:
        """DFS over call chains from ``key``; yields
        ``(chain_of_caller_keys, edge)`` for every edge reachable without
        revisiting a function already on the current chain (cycle-safe).
        """
        def rec(k: str, chain: Tuple[str, ...]) -> Iterator:
            if max_depth is not None and len(chain) > max_depth:
                return
            for edge in self.edges.get(k, []):
                if edge.callee.key in chain or edge.callee.key == key:
                    continue
                yield chain, edge
                yield from rec(edge.callee.key, chain + (edge.callee.key,))

        yield from rec(key, (key,))
