"""Static call graph over a batonlint :class:`~.project.Project`.

Edges are the calls :meth:`Project.resolve_call_multi` can pin down
statically — same-module helpers, ``self.method`` (resolved through
the class hierarchy: nearest inherited definition PLUS every known
subclass override, so a lock acquired in an overriding method is
visible to callers of the base method), ``super()`` chains, imported
symbols, and ``alias.func`` through an imported module.  Each edge
keeps its call-site node so downstream rules (lock-order, staleness)
can report the path a hazard travels, not just its endpoints; a call
site with several dispatch candidates contributes one edge per
candidate.

Reflection calls the resolver CAN pin down also contribute edges:
``getattr(self, "handle_" + x)(...)`` fans out to every hierarchy
method matching the literal prefix, and dict-literal dispatch tables
(function-local, ``self.X``, or module-level) fan ``tbl[k](...)`` /
``tbl.get(k)(...)`` out to every table value.  Calls that remain
dynamic (computed names with no literal prefix, HOFs through opaque
objects) are simply absent, so rules built on the graph UNDER-report
across those boundaries and say so in their docs rather than guessing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.project import FunctionInfo, Project

__all__ = ["CallEdge", "CallGraph"]


def _is_self_call(call: ast.Call) -> bool:
    """``self.m()`` / ``cls.m()`` / ``super().m()`` — calls whose
    receiver is the caller's own instance, so the callee's ``self.*``
    effects land on the caller's state."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if isinstance(func.value, ast.Name) and func.value.id in ("self", "cls"):
        return True
    return (
        isinstance(func.value, ast.Call)
        and isinstance(func.value.func, ast.Name)
        and func.value.func.id == "super"
    )


def _local_dispatch_tables(fn_node: ast.AST) -> Dict[str, Tuple[str, ...]]:
    """Function-local ``tbl = {k: handler, ...}`` dispatch tables."""
    from baton_tpu.analysis.project import _dict_literal_refs

    out: Dict[str, Tuple[str, ...]] = {}
    for node in au.walk_shallow(fn_node):
        if not isinstance(node, ast.Assign):
            continue
        refs = _dict_literal_refs(node.value)
        if refs is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.setdefault(t.id, refs)
    return out


@dataclasses.dataclass
class CallEdge:
    caller: FunctionInfo
    callee: FunctionInfo
    node: ast.Call                # the call site, in caller's module
    via_self: bool = False        # receiver is the caller's own instance

    @property
    def line(self) -> int:
        return self.node.lineno


class CallGraph:
    """``caller key -> [CallEdge]``; keys are ``module:Qual.name``."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {
            fn.key: fn for fn in project.functions()
        }
        self.edges: Dict[str, List[CallEdge]] = {}
        for fn in project.functions():
            out: List[CallEdge] = []
            local_tables = _local_dispatch_tables(fn.node)
            for node in au.walk_shallow(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                seen_here: set = set()
                for callee in project.resolve_call_multi(
                    fn.module, fn.class_name, node
                ):
                    if callee.key != fn.key:
                        seen_here.add(callee.key)
                        out.append(
                            CallEdge(fn, callee, node, _is_self_call(node))
                        )
                for callee, via_self in project.reflection_targets(
                    fn.module, fn.class_name, node, local_tables
                ):
                    if callee.key != fn.key and callee.key not in seen_here:
                        seen_here.add(callee.key)
                        out.append(CallEdge(fn, callee, node, via_self))
            self.edges[fn.key] = out

    def callees(self, key: str) -> List[CallEdge]:
        return self.edges.get(key, [])

    def walk_from(
        self, key: str, max_depth: Optional[int] = None
    ) -> Iterator[Tuple[Tuple[str, ...], CallEdge]]:
        """DFS over call chains from ``key``; yields
        ``(chain_of_caller_keys, edge)`` for every edge reachable without
        revisiting a function already on the current chain (cycle-safe).
        """
        def rec(k: str, chain: Tuple[str, ...]) -> Iterator:
            if max_depth is not None and len(chain) > max_depth:
                return
            for edge in self.edges.get(k, []):
                if edge.callee.key in chain or edge.callee.key == key:
                    continue
                yield chain, edge
                yield from rec(edge.callee.key, chain + (edge.callee.key,))

        yield from rec(key, (key,))
