"""Static call graph over a batonlint :class:`~.project.Project`.

Edges are the calls :meth:`Project.resolve_call_multi` can pin down
statically — same-module helpers, ``self.method`` (resolved through
the class hierarchy: nearest inherited definition PLUS every known
subclass override, so a lock acquired in an overriding method is
visible to callers of the base method), ``super()`` chains, imported
symbols, and ``alias.func`` through an imported module.  Each edge
keeps its call-site node so downstream rules (lock-order, staleness)
can report the path a hazard travels, not just its endpoints; a call
site with several dispatch candidates contributes one edge per
candidate.

Calls the resolver cannot pin down (``getattr``, HOFs, calls through
arbitrary objects) are simply absent, so rules built on the graph
UNDER-report across those boundaries and say so in their docs rather
than guessing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.project import FunctionInfo, Project

__all__ = ["CallEdge", "CallGraph"]


def _is_self_call(call: ast.Call) -> bool:
    """``self.m()`` / ``cls.m()`` / ``super().m()`` — calls whose
    receiver is the caller's own instance, so the callee's ``self.*``
    effects land on the caller's state."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if isinstance(func.value, ast.Name) and func.value.id in ("self", "cls"):
        return True
    return (
        isinstance(func.value, ast.Call)
        and isinstance(func.value.func, ast.Name)
        and func.value.func.id == "super"
    )


@dataclasses.dataclass
class CallEdge:
    caller: FunctionInfo
    callee: FunctionInfo
    node: ast.Call                # the call site, in caller's module
    via_self: bool = False        # receiver is the caller's own instance

    @property
    def line(self) -> int:
        return self.node.lineno


class CallGraph:
    """``caller key -> [CallEdge]``; keys are ``module:Qual.name``."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {
            fn.key: fn for fn in project.functions()
        }
        self.edges: Dict[str, List[CallEdge]] = {}
        for fn in project.functions():
            out: List[CallEdge] = []
            for node in au.walk_shallow(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in project.resolve_call_multi(
                    fn.module, fn.class_name, node
                ):
                    if callee.key != fn.key:
                        out.append(
                            CallEdge(fn, callee, node, _is_self_call(node))
                        )
            self.edges[fn.key] = out

    def callees(self, key: str) -> List[CallEdge]:
        return self.edges.get(key, [])

    def walk_from(
        self, key: str, max_depth: Optional[int] = None
    ) -> Iterator[Tuple[Tuple[str, ...], CallEdge]]:
        """DFS over call chains from ``key``; yields
        ``(chain_of_caller_keys, edge)`` for every edge reachable without
        revisiting a function already on the current chain (cycle-safe).
        """
        def rec(k: str, chain: Tuple[str, ...]) -> Iterator:
            if max_depth is not None and len(chain) > max_depth:
                return
            for edge in self.edges.get(k, []):
                if edge.callee.key in chain or edge.callee.key == key:
                    continue
                yield chain, edge
                yield from rec(edge.callee.key, chain + (edge.callee.key,))

        yield from rec(key, (key,))
