"""SARIF 2.1.0 reporter for batonlint.

One ``run`` with batonlint as the tool driver, one ``result`` per
finding, one ``reportingDescriptor`` per registered rule, and one
``toolExecutionNotification`` per engine error — enough for code
scanning UIs to ingest findings with stable rule ids and clickable
regions.  Columns are 1-based in SARIF; batonlint columns are 0-based
AST offsets, hence the ``+1``.
"""

from __future__ import annotations

import json
import pathlib

from baton_tpu.analysis.engine import (
    Report,
    all_rules,
    finding_fingerprints,
)

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _artifact_uri(path: str) -> str:
    return pathlib.PurePath(path).as_posix()


def sarif_dict(report: Report) -> dict:
    rules = all_rules()
    results = []
    fps = finding_fingerprints(report.findings)
    for f, fp in zip(report.findings, fps):
        results.append({
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "partialFingerprints": {"batonlintFingerprint/v1": fp},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": _artifact_uri(f.path),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,
                    },
                },
            }],
        })
    notifications = [
        {
            "level": "error",
            "message": {"text": err},
        }
        for err in report.errors
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "batonlint",
                    "informationUri":
                        "https://github.com/baton-tpu/baton-tpu",
                    "rules": [
                        {
                            "id": rule,
                            "shortDescription": {"text": title},
                        }
                        for rule, title in sorted(rules.items())
                    ],
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file:///"},
            },
            "invocations": [{
                "executionSuccessful": not report.errors,
                "toolExecutionNotifications": notifications,
            }],
            "results": results,
        }],
    }


def format_sarif(report: Report) -> str:
    return json.dumps(sarif_dict(report), indent=2, sort_keys=True)
