"""batonlint — project-native static analysis for baton_tpu.

PRs 1-3 bought durability and a pipelined data plane by enforcing
delicate conventions *by hand*: no blocking decode/fold work on the
asyncio event loop, every request body read through a byte cap, no
``await`` of network primitives while holding a state lock, no Python
side effects inside ``jit``/``shard_map``-traced functions, and a
metrics-counter namespace that matches the declared registry. Nothing
checked any of that — ``http_worker.py`` regressed to an uncapped
``await request.read()`` within one PR of the cap landing.

This package is the machine enforcement: a stdlib-``ast`` lint engine
(:mod:`~baton_tpu.analysis.engine`) with a checker registry, per-line
suppressions (``# batonlint: allow[RULE]``), text/JSON reporters, and a
CLI (``python -m baton_tpu.analysis [paths]``).  Since the
whole-program layer landed (:mod:`~baton_tpu.analysis.project` builds
a cross-module symbol table with class-hierarchy analysis,
:mod:`~baton_tpu.analysis.callgraph` a static call graph over it, and
:mod:`~baton_tpu.analysis.summaries` bottom-up fixpoint function
summaries over the call graph's SCCs), rules come in two scopes:
per-file (``Checker``) and project-wide (``ProjectChecker`` — every
file on the command line analyzed as one program).  Rules:

=======  ==============================================================
BTL000   stale suppression: a ``# batonlint: allow[RULE]`` comment
         that no longer silences any finding — stale allows hide the
         next real instance at that line
BTL001   blocking call (file I/O, ``time.sleep``, ``pickle.loads``,
         ``zlib.*``, ``.block_until_ready()``, ``jax.device_get``)
         reachable from an ``async def`` in ``baton_tpu/server/`` —
         directly or through sync helpers at any call-graph depth,
         cross-module, with the witness chain  [project-wide]
BTL002   ``await`` of a network/queue primitive while holding an
         asyncio lock — lexically or through awaited coroutines'
         fixpoint summaries; lock-acquisition-order CYCLES over the
         whole-program call graph (multi-hop, cross-module ABBA
         pairs, both acquisition paths reported); ``self.*`` lock
         identity normalizes to the root ancestor class, so
         subclass-override acquisitions unify  [project-wide]
BTL003   shared-state snapshot (``self.reg.get(k)``, guarded
         attribute, one-hop helper) used after an ``await`` /
         ``to_thread`` boundary without an identity re-check — the
         abort/restart TOCTOU that downgraded secure aggregation;
         branch-sensitive: a re-check in an ``if`` whose arm
         returns/raises installs the guard, and staleness on a
         terminating branch does not leak past the merge
BTL004   async shared-state race in ``server/`` classes: a ``self.*``
         snapshot taken before an ``await`` and written back after it
         from the stale value (lost update), or a lockless write to
         an attribute that another method writes under a lock held
         across an await — fix with the lock, or compare-and-
         invalidate against the decision value  [project-wide]
BTL010   tracer hygiene inside ``@jax.jit``/``shard_map`` functions
         (``print``, ``.item()``, ``float()``/``int()`` on traced
         values, ``np.asarray``, module-state mutation); traced
         values followed by dataflow taint through assignments,
         ``self.*`` writes, containers, and call results; calls into
         project helpers (any depth, cross-module, CHA dispatch)
         whose summaries contain such ops are flagged at the call
         site with the witness chain  [project-wide]
BTL011   ``jax.jit`` applied to a round-step/training function whose
         parameters carry model-state pytrees (``params``,
         ``opt_states``, ``anchors``...) with no donation decision —
         pass ``donate_argnums`` (``()`` records an audited no) or
         suppress with a justified ``# batonlint: allow[BTL011]``
BTL020   raw ``request.read()`` / uncapped ``request.json()`` in an
         aiohttp handler (use ``utils.read_body_capped`` /
         ``utils.read_json_capped``)
BTL030   metrics counter used in ``server/`` but not declared in
         ``baton_tpu/utils/metrics.py``
=======  ==============================================================

The repo itself must stay lint-clean: ``tests/test_analysis.py::
test_repo_is_lint_clean`` runs this engine over ``baton_tpu/`` and
asserts zero findings, and CI runs the CLI before the test suite
(uploading the ``--json-out`` report as a build artifact).
``--changed-only`` lints the whole project but reports only files
touched per ``git diff`` — the fast pre-commit mode.  ``--cache``
persists per-file local summaries keyed by content hash
(``.batonlint_cache.json``) so unchanged files skip extraction on the
next run (hit/miss counts surface in ``--json-out``), and ``--sarif``
writes a SARIF 2.1.0 report for code-scanning UIs.
"""

from baton_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Report,
    all_rules,
    run_paths,
    run_project_sources,
    run_source,
)
