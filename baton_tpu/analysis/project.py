"""Whole-program model for batonlint: modules, symbols, imports.

The per-file checkers see one ``ast.Module`` at a time; everything a
cross-module rule needs — "which function does ``secure.dh_shared_seed``
resolve to", "which class owns ``_register_lock``" — lives here.  A
:class:`Project` is built once per lint run from every file on the
command line, so project-scoped checkers (``ProjectChecker`` in the
engine) can follow calls across module boundaries.

Resolution is deliberately syntactic (no imports are executed, same
contract as the rest of batonlint):

* module names come from the filesystem when the file exists (walking
  up through ``__init__.py`` packages) and from the given path string
  for in-memory fixtures, so ``baton_tpu/server/fixture.py`` is module
  ``baton_tpu.server.fixture`` either way;
* ``import a.b as x`` / ``from a.b import f`` bind local aliases to
  dotted targets; relative imports resolve against the module's own
  package;
* a call resolves through (1) same-module functions/methods
  (``self.helper`` -> ``Class.helper``), (2) an imported symbol, or
  (3) ``alias.attr`` where the alias names a project module.  Dynamic
  dispatch, inheritance, and re-exports are out of scope — a resolver
  miss returns ``None`` and the caller degrades to per-file behavior.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au

__all__ = ["FunctionInfo", "ModuleInfo", "Project"]


@dataclasses.dataclass
class FunctionInfo:
    """One def/async def, with enough context to name and revisit it."""

    qualname: str                 # "Class.method" or bare name
    class_name: Optional[str]
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"

    @property
    def key(self) -> str:
        """Project-unique id: ``module.dotted.name:Qual.name``."""
        return f"{self.module.name}:{self.qualname}"

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


class ModuleInfo:
    """One parsed source file plus its symbol table."""

    def __init__(
        self,
        path: str,
        source: str,
        tree: ast.Module,
        name: str,
        counter_registry: Optional[Tuple[frozenset, tuple]] = None,
    ) -> None:
        self.path = path
        self.posix_path = pathlib.PurePath(path).as_posix()
        self.parts = pathlib.PurePath(path).parts
        self.source = source
        self.tree = tree
        self.name = name
        self.counter_registry = counter_registry
        self.functions: Dict[str, FunctionInfo] = {}
        for qual, cls, node in au.iter_function_defs(tree):
            self.functions.setdefault(
                qual, FunctionInfo(qual, cls, node, self)
            )
        self.imports = _collect_imports(tree, name)


def _module_name_for(path: str) -> str:
    """Dotted module name for ``path``.

    Real files walk up while ``__init__.py`` siblings exist, so
    ``/any/prefix/baton_tpu/server/x.py`` -> ``baton_tpu.server.x``.
    Nonexistent (fixture) paths fall back to the path string itself:
    ``fixtures/liba.py`` -> ``fixtures.liba``.
    """
    p = pathlib.Path(path)
    stem_parts: List[str] = [] if p.stem == "__init__" else [p.stem]
    if p.is_file():
        parent = p.resolve().parent
        parts = list(stem_parts)
        while (parent / "__init__.py").is_file():
            parts.insert(0, parent.name)
            parent = parent.parent
        return ".".join(parts) or p.stem
    pure = pathlib.PurePath(path)
    parts = [x for x in pure.parts[:-1] if x not in ("/", "\\", "..", ".")]
    return ".".join(parts + stem_parts) or p.stem


def _collect_imports(tree: ast.Module, module_name: str) -> Dict[str, str]:
    """``{local alias: dotted target}`` for every import in the module
    (function-level imports included — ``from . import secure`` inside a
    handler binds the same way for resolution purposes)."""
    imports: Dict[str, str] = {}
    pkg_parts = module_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    imports.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(
                    base_parts + ([node.module] if node.module else [])
                )
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{base}.{alias.name}" if base else alias.name
                imports[alias.asname or alias.name] = target
    return imports


class Project:
    """All modules of one lint run, indexed by path and dotted name."""

    def __init__(self) -> None:
        self.modules: List[ModuleInfo] = []
        self.by_path: Dict[str, ModuleInfo] = {}
        self.by_name: Dict[str, ModuleInfo] = {}

    @classmethod
    def from_parsed(
        cls,
        entries: Iterable[Tuple[str, str, ast.Module,
                                Optional[Tuple[frozenset, tuple]]]],
    ) -> "Project":
        """Build from ``(path, source, tree, counter_registry)`` tuples
        (the engine parses; a file that failed to parse never gets
        here)."""
        project = cls()
        for path, source, tree, registry in entries:
            mod = ModuleInfo(path, source, tree, _module_name_for(path),
                             counter_registry=registry)
            project.modules.append(mod)
            project.by_path[path] = mod
            # first module wins on a name collision (e.g. two fixture
            # trees shipping an identically-named module)
            project.by_name.setdefault(mod.name, mod)
        return project

    def functions(self) -> Iterable[FunctionInfo]:
        for mod in self.modules:
            yield from mod.functions.values()

    def function_by_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        """``baton_tpu.server.secure.dh_shared_seed`` -> FunctionInfo.

        Tries the longest module prefix first so ``pkg.mod.Class.method``
        resolves even when ``pkg.mod.Class`` isn't itself a module.
        """
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:i]))
            if mod is not None:
                hit = mod.functions.get(".".join(parts[i:]))
                if hit is not None:
                    return hit
        return None

    def resolve_call(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> Optional[FunctionInfo]:
        """Best-effort static resolution of a call expression made from
        inside ``mod`` (``class_name`` = enclosing class, for ``self.``)."""
        local = au.resolve_local_call(call, class_name)
        if local is not None:
            hit = mod.functions.get(local)
            if hit is not None:
                return hit
            if "." not in local:
                target = mod.imports.get(local)
                if target is not None:
                    return self.function_by_dotted(target)
            return None
        dotted = au.dotted_name(call.func)
        if dotted is None or "." not in dotted:
            return None
        root, rest = dotted.split(".", 1)
        target = self.imports_target(mod, root)
        if target is None:
            return None
        return self.function_by_dotted(f"{target}.{rest}")

    def imports_target(self, mod: ModuleInfo, alias: str) -> Optional[str]:
        return mod.imports.get(alias)
