"""Whole-program model for batonlint: modules, symbols, imports.

The per-file checkers see one ``ast.Module`` at a time; everything a
cross-module rule needs — "which function does ``secure.dh_shared_seed``
resolve to", "which class owns ``_register_lock``" — lives here.  A
:class:`Project` is built once per lint run from every file on the
command line, so project-scoped checkers (``ProjectChecker`` in the
engine) can follow calls across module boundaries.

Resolution is deliberately syntactic (no imports are executed, same
contract as the rest of batonlint):

* module names come from the filesystem when the file exists (walking
  up through ``__init__.py`` packages) and from the given path string
  for in-memory fixtures, so ``baton_tpu/server/fixture.py`` is module
  ``baton_tpu.server.fixture`` either way;
* ``import a.b as x`` / ``from a.b import f`` bind local aliases to
  dotted targets; relative imports resolve against the module's own
  package;
* a call resolves through (1) same-module functions/methods
  (``self.helper`` -> ``Class.helper``), (2) an imported symbol, or
  (3) ``alias.attr`` where the alias names a project module;
* since the class-hierarchy layer landed, ``self.method()`` also
  resolves through inheritance: the nearest definition up the base
  chain PLUS every override in known subclasses (class-hierarchy
  analysis — the receiver's dynamic type may be any subclass of the
  enclosing class), and ``super().method()`` resolves to the nearest
  base-class definition;
* the common reflection idioms resolve too:
  ``getattr(self, "handle_" + x)(...)`` (and the f-string spelling)
  dispatches to every method of the class hierarchy whose name starts
  with the literal prefix, and dict-literal dispatch tables —
  function-local ``tbl = {...}``, instance ``self._table = {...}``,
  or module-level ``TABLE = {...}`` whose values are resolvable
  callable references — dispatch ``tbl[k](...)`` / ``tbl.get(k)(...)``
  to every value.  Truly dynamic dispatch (computed attribute names
  with no literal prefix, HOFs through opaque objects) remains out of
  scope — a resolver miss returns ``None``/``[]`` and the caller
  degrades to per-file behavior.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au

__all__ = ["ClassInfo", "FunctionInfo", "ModuleInfo", "Project"]


@dataclasses.dataclass
class FunctionInfo:
    """One def/async def, with enough context to name and revisit it."""

    qualname: str                 # "Class.method" or bare name
    class_name: Optional[str]
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"

    @property
    def key(self) -> str:
        """Project-unique id: ``module.dotted.name:Qual.name``."""
        return f"{self.module.name}:{self.qualname}"

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


def _dict_literal_refs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Values of a dict literal as dotted callable refs, when EVERY
    non-constant value is one — the dispatch-table shape.  Returns None
    for anything else (a dict of data is not a dispatch table)."""
    if not isinstance(node, ast.Dict) or not node.values:
        return None
    refs = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            return None
        d = au.dotted_name(v)
        if d is None:
            return None
        refs.append(d)
    return tuple(refs)


def _str_pattern(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """``(exact, prefix)`` for the attribute-name expression of a
    ``getattr`` call: a string constant gives ``exact``; ``"pre_" + x``
    and ``f"pre_{x}"`` give ``prefix``; anything else ``(None, None)``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, None
    if (
        isinstance(node, ast.BinOp)
        and isinstance(node.op, ast.Add)
        and isinstance(node.left, ast.Constant)
        and isinstance(node.left.value, str)
    ):
        return None, node.left.value
    if (
        isinstance(node, ast.JoinedStr)
        and node.values
        and isinstance(node.values[0], ast.Constant)
        and isinstance(node.values[0].value, str)
    ):
        return None, node.values[0].value
    return None, None


@dataclasses.dataclass
class ClassInfo:
    """One class definition: enough to build the inheritance graph."""

    name: str                     # bare class name
    module: "ModuleInfo"
    node: ast.ClassDef
    base_names: Tuple[str, ...]   # raw dotted base expressions

    @property
    def key(self) -> str:
        """Project-unique id: ``module.dotted.name:ClassName``."""
        return f"{self.module.name}:{self.name}"

    def method(self, name: str) -> Optional["FunctionInfo"]:
        """The method defined ON this class (no inheritance walk)."""
        return self.module.functions.get(f"{self.name}.{name}")


class ModuleInfo:
    """One parsed source file plus its symbol table."""

    def __init__(
        self,
        path: str,
        source: str,
        tree: ast.Module,
        name: str,
        counter_registry: Optional[Tuple[frozenset, tuple]] = None,
    ) -> None:
        self.path = path
        self.posix_path = pathlib.PurePath(path).as_posix()
        self.parts = pathlib.PurePath(path).parts
        self.source = source
        self.tree = tree
        self.name = name
        self.counter_registry = counter_registry
        self.functions: Dict[str, FunctionInfo] = {}
        for qual, cls, node in au.iter_function_defs(tree):
            self.functions.setdefault(
                qual, FunctionInfo(qual, cls, node, self)
            )
        self.imports = _collect_imports(tree, name)
        self.classes: Dict[str, ClassInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases = tuple(
                    b for b in (au.dotted_name(base) for base in node.bases)
                    if b is not None
                )
                self.classes.setdefault(
                    node.name, ClassInfo(node.name, self, node, bases)
                )
        self._global_names: Optional[frozenset] = None
        self._dispatch_tables: Optional[Dict[str, Tuple[str, ...]]] = None
        self._class_tables: Optional[
            Dict[Tuple[str, str], Tuple[str, ...]]
        ] = None

    @property
    def global_names(self) -> frozenset:
        """Module-level mutable bindings: names assigned at module scope
        that are not imports, defs, or classes — the state a worker
        thread and the event loop could race on."""
        if self._global_names is None:
            bound: set = set()
            for stmt in self.tree.body:
                targets: list = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    targets = [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        bound.update(
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        )
            self._global_names = frozenset(
                bound - set(self.imports) - set(self.functions)
                - set(self.classes)
            )
        return self._global_names

    @property
    def dispatch_tables(self) -> Dict[str, Tuple[str, ...]]:
        """Module-level ``NAME = {k: handler, ...}`` dict literals whose
        values are callable refs — ``{NAME: (ref, ...)}``."""
        if self._dispatch_tables is None:
            out: Dict[str, Tuple[str, ...]] = {}
            for stmt in self.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                refs = _dict_literal_refs(stmt.value)
                if refs is None:
                    continue
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.setdefault(t.id, refs)
            self._dispatch_tables = out
        return self._dispatch_tables

    @property
    def class_dispatch_tables(self) -> Dict[Tuple[str, str], Tuple[str, ...]]:
        """``self.X = {k: self.handler, ...}`` tables assigned in any
        method — ``{(class_name, attr): (ref, ...)}``."""
        if self._class_tables is None:
            out: Dict[Tuple[str, str], Tuple[str, ...]] = {}
            for fi in self.functions.values():
                if fi.class_name is None:
                    continue
                for node in au.walk_shallow(fi.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    refs = _dict_literal_refs(node.value)
                    if refs is None:
                        continue
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ("self", "cls")
                        ):
                            out.setdefault(
                                (fi.class_name, t.attr), refs
                            )
            self._class_tables = out
        return self._class_tables


def _module_name_for(path: str) -> str:
    """Dotted module name for ``path``.

    Real files walk up while ``__init__.py`` siblings exist, so
    ``/any/prefix/baton_tpu/server/x.py`` -> ``baton_tpu.server.x``.
    Nonexistent (fixture) paths fall back to the path string itself:
    ``fixtures/liba.py`` -> ``fixtures.liba``.
    """
    p = pathlib.Path(path)
    stem_parts: List[str] = [] if p.stem == "__init__" else [p.stem]
    if p.is_file():
        parent = p.resolve().parent
        parts = list(stem_parts)
        while (parent / "__init__.py").is_file():
            parts.insert(0, parent.name)
            parent = parent.parent
        return ".".join(parts) or p.stem
    pure = pathlib.PurePath(path)
    parts = [x for x in pure.parts[:-1] if x not in ("/", "\\", "..", ".")]
    return ".".join(parts + stem_parts) or p.stem


def _collect_imports(tree: ast.Module, module_name: str) -> Dict[str, str]:
    """``{local alias: dotted target}`` for every import in the module
    (function-level imports included — ``from . import secure`` inside a
    handler binds the same way for resolution purposes)."""
    imports: Dict[str, str] = {}
    pkg_parts = module_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    imports.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(
                    base_parts + ([node.module] if node.module else [])
                )
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{base}.{alias.name}" if base else alias.name
                imports[alias.asname or alias.name] = target
    return imports


class Project:
    """All modules of one lint run, indexed by path and dotted name."""

    def __init__(self) -> None:
        self.modules: List[ModuleInfo] = []
        self.by_path: Dict[str, ModuleInfo] = {}
        self.by_name: Dict[str, ModuleInfo] = {}
        self._hier: Optional[Tuple[Dict[str, List[str]],
                                   Dict[str, List[str]],
                                   Dict[str, ClassInfo]]] = None

    @classmethod
    def from_parsed(
        cls,
        entries: Iterable[Tuple[str, str, ast.Module,
                                Optional[Tuple[frozenset, tuple]]]],
    ) -> "Project":
        """Build from ``(path, source, tree, counter_registry)`` tuples
        (the engine parses; a file that failed to parse never gets
        here)."""
        project = cls()
        for path, source, tree, registry in entries:
            mod = ModuleInfo(path, source, tree, _module_name_for(path),
                             counter_registry=registry)
            project.modules.append(mod)
            project.by_path[path] = mod
            # first module wins on a name collision (e.g. two fixture
            # trees shipping an identically-named module)
            project.by_name.setdefault(mod.name, mod)
        return project

    def functions(self) -> Iterable[FunctionInfo]:
        for mod in self.modules:
            yield from mod.functions.values()

    def function_by_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        """``baton_tpu.server.secure.dh_shared_seed`` -> FunctionInfo.

        Tries the longest module prefix first so ``pkg.mod.Class.method``
        resolves even when ``pkg.mod.Class`` isn't itself a module.
        """
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:i]))
            if mod is not None:
                hit = mod.functions.get(".".join(parts[i:]))
                if hit is not None:
                    return hit
        return None

    # -- class hierarchy (CHA) -----------------------------------------
    def _hierarchy(self):
        """``(parents, children, by_key)`` over every known class; built
        once per project, cycle-tolerant (a recursive base chain just
        stops unifying where the cycle closes)."""
        if self._hier is not None:
            return self._hier
        by_key: Dict[str, ClassInfo] = {}
        for mod in self.modules:
            for ci in mod.classes.values():
                by_key.setdefault(ci.key, ci)
        parents: Dict[str, List[str]] = {}
        children: Dict[str, List[str]] = {}
        for ci in by_key.values():
            for base in ci.base_names:
                parent = self._resolve_class_name(ci.module, base)
                if parent is None or parent.key == ci.key:
                    continue
                parents.setdefault(ci.key, []).append(parent.key)
                children.setdefault(parent.key, []).append(ci.key)
        self._hier = (parents, children, by_key)
        return self._hier

    def _class_by_dotted(self, dotted: str) -> Optional[ClassInfo]:
        parts = dotted.split(".")
        if len(parts) < 2:
            return None
        mod = self.by_name.get(".".join(parts[:-1]))
        if mod is None:
            return None
        return mod.classes.get(parts[-1])

    def _resolve_class_name(
        self, mod: ModuleInfo, dotted: str
    ) -> Optional[ClassInfo]:
        """A base-class expression (``Base``, ``pkg.Base``, imported
        alias) -> the ClassInfo it names, when it is a project class."""
        root, _, rest = dotted.partition(".")
        if not rest:
            ci = mod.classes.get(dotted)
            if ci is not None:
                return ci
            target = mod.imports.get(dotted)
            return self._class_by_dotted(target) if target else None
        target = mod.imports.get(root)
        if target is not None:
            return self._class_by_dotted(f"{target}.{rest}")
        return self._class_by_dotted(dotted)

    def class_info(
        self, mod: ModuleInfo, class_name: Optional[str]
    ) -> Optional[ClassInfo]:
        if class_name is None:
            return None
        return mod.classes.get(class_name)

    def ancestors(self, ci: ClassInfo) -> List[ClassInfo]:
        """Base classes of ``ci``, nearest first (BFS, cycle-safe)."""
        parents, _children, by_key = self._hierarchy()
        out: List[ClassInfo] = []
        seen = {ci.key}
        frontier = list(parents.get(ci.key, []))
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                if key in seen:
                    continue
                seen.add(key)
                out.append(by_key[key])
                nxt.extend(parents.get(key, []))
            frontier = nxt
        return out

    def descendants(self, ci: ClassInfo) -> List[ClassInfo]:
        """Known subclasses of ``ci``, transitively (BFS, cycle-safe)."""
        _parents, children, by_key = self._hierarchy()
        out: List[ClassInfo] = []
        seen = {ci.key}
        frontier = list(children.get(ci.key, []))
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                if key in seen:
                    continue
                seen.add(key)
                out.append(by_key[key])
                nxt.extend(children.get(key, []))
            frontier = nxt
        return out

    def root_class_name(
        self, mod: ModuleInfo, class_name: Optional[str]
    ) -> Optional[str]:
        """Bare name of the topmost known ancestor of ``class_name`` —
        the namespace ``self.attr`` state and locks unify under, so a
        lock acquired in ``Sub`` and one in ``Base`` name the same
        object when ``Sub(Base)``."""
        if class_name is None:
            return None
        ci = self.class_info(mod, class_name)
        if ci is None:
            return class_name
        chain = self.ancestors(ci)
        return chain[-1].name if chain else ci.name

    def resolve_method(
        self, ci: ClassInfo, method: str
    ) -> Optional[FunctionInfo]:
        """Nearest definition of ``method`` on ``ci`` or up its bases."""
        hit = ci.method(method)
        if hit is not None:
            return hit
        for base in self.ancestors(ci):
            hit = base.method(method)
            if hit is not None:
                return hit
        return None

    def method_candidates(
        self, ci: ClassInfo, method: str
    ) -> List[FunctionInfo]:
        """CHA dispatch set for ``self.method()`` in class ``ci``: the
        nearest inherited definition plus every override in known
        subclasses (the receiver may be any subclass instance)."""
        out: List[FunctionInfo] = []
        seen: set = set()

        def add(fn: Optional[FunctionInfo]) -> None:
            if fn is not None and fn.key not in seen:
                seen.add(fn.key)
                out.append(fn)

        add(self.resolve_method(ci, method))
        for sub in self.descendants(ci):
            add(sub.method(method))
        return out

    # -- call resolution -----------------------------------------------
    def resolve_call_multi(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> List[FunctionInfo]:
        """Every function this call may statically dispatch to.

        ``self.method()``/``cls.method()`` resolve through the class
        hierarchy (nearest definition up the bases plus all subclass
        overrides); ``super().method()`` to the nearest base
        definition; everything else to at most one candidate via the
        module symbol table."""
        func = call.func
        ci = self.class_info(mod, class_name)
        # super().method(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Name)
            and func.value.func.id == "super"
        ):
            if ci is None:
                return []
            own = ci.method(func.attr)
            for base in self.ancestors(ci):
                hit = base.method(func.attr)
                if hit is not None and (own is None or hit.key != own.key):
                    return [hit]
            return []
        # self.method(...) / cls.method(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
        ):
            if ci is not None:
                hits = self.method_candidates(ci, func.attr)
                if hits:
                    return hits
            # no hierarchy info: fall through to the legacy single-shot
        single = self._resolve_call_single(mod, class_name, call)
        return [single] if single is not None else []

    def resolve_call(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> Optional[FunctionInfo]:
        """Best-effort single-target resolution (primary candidate —
        the nearest-MRO definition for ``self.`` calls)."""
        hits = self.resolve_call_multi(mod, class_name, call)
        return hits[0] if hits else None

    def _resolve_call_single(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> Optional[FunctionInfo]:
        local = au.resolve_local_call(call, class_name)
        if local is not None:
            hit = mod.functions.get(local)
            if hit is not None:
                return hit
            if "." not in local:
                target = mod.imports.get(local)
                if target is not None:
                    return self.function_by_dotted(target)
            return None
        dotted = au.dotted_name(call.func)
        if dotted is None or "." not in dotted:
            return None
        root, rest = dotted.split(".", 1)
        target = self.imports_target(mod, root)
        if target is None:
            return None
        return self.function_by_dotted(f"{target}.{rest}")

    def imports_target(self, mod: ModuleInfo, alias: str) -> Optional[str]:
        return mod.imports.get(alias)

    # -- reference / reflection resolution ------------------------------
    def resolve_ref(
        self, mod: ModuleInfo, class_name: Optional[str], ref: str
    ) -> List[FunctionInfo]:
        """A raw callable *reference* (not a call) -> candidate
        functions: ``"self.handle_x"`` through the class hierarchy,
        ``"run"`` to a nested/sibling def or module function or import,
        ``"mod.fn"`` through the symbol table."""
        if not ref:
            return []
        root, _, rest = ref.partition(".")
        if root in ("self", "cls") and rest and "." not in rest:
            ci = self.class_info(mod, class_name)
            if ci is not None:
                hits = self.method_candidates(ci, rest)
                if hits:
                    return hits
            if class_name is not None:
                hit = mod.functions.get(f"{class_name}.{rest}")
                return [hit] if hit is not None else []
            return []
        if not rest:  # bare name
            quals = [ref] if class_name is None else [
                f"{class_name}.{ref}", ref,
            ]
            for qual in quals:
                hit = mod.functions.get(qual)
                if hit is not None:
                    return [hit]
            target = mod.imports.get(ref)
            if target is not None:
                hit = self.function_by_dotted(target)
                return [hit] if hit is not None else []
            return []
        hit = mod.functions.get(ref)  # literal "Class.method"
        if hit is not None:
            return [hit]
        target = mod.imports.get(root)
        if target is not None:
            hit = self.function_by_dotted(f"{target}.{rest}")
            return [hit] if hit is not None else []
        hit = self.function_by_dotted(ref)
        return [hit] if hit is not None else []

    def methods_with_prefix(
        self, mod: ModuleInfo, class_name: Optional[str], prefix: str
    ) -> List[FunctionInfo]:
        """Every method in ``class_name``'s hierarchy whose name starts
        with ``prefix`` — the ``getattr(self, "handle_" + x)`` dispatch
        set.  An empty prefix resolves to nothing (that is not a
        statically-known suffix set, it is full dynamism)."""
        if not prefix or class_name is None:
            return []
        out: List[FunctionInfo] = []
        seen: set = set()

        def scan(cls_name: str, cls_mod: ModuleInfo) -> None:
            want = f"{cls_name}."
            for qual, fi in cls_mod.functions.items():
                if not qual.startswith(want):
                    continue
                method = qual[len(want):]
                if "." in method or not method.startswith(prefix):
                    continue
                if fi.key not in seen:
                    seen.add(fi.key)
                    out.append(fi)

        ci = self.class_info(mod, class_name)
        if ci is None:
            scan(class_name, mod)
            return out
        for c in [ci, *self.ancestors(ci), *self.descendants(ci)]:
            scan(c.name, c.module)
        return out

    def reflection_targets(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
        local_tables: Optional[Dict[str, Tuple[str, ...]]] = None,
    ) -> List[Tuple[FunctionInfo, bool]]:
        """``(callee, via_self)`` candidates for the reflection call
        shapes: ``getattr(self, "pre_" + x)(...)`` over the literal
        prefix, and dispatch-table calls ``tbl[k](...)`` /
        ``tbl.get(k)(...)`` through function-local, ``self.X``, or
        module-level dict-literal tables."""
        func = call.func
        # getattr(self, <name-expr>)(...)
        if (
            isinstance(func, ast.Call)
            and isinstance(func.func, ast.Name)
            and func.func.id == "getattr"
            and len(func.args) >= 2
            and isinstance(func.args[0], ast.Name)
            and func.args[0].id in ("self", "cls")
        ):
            exact, prefix = _str_pattern(func.args[1])
            if exact is not None:
                return [
                    (fi, True)
                    for fi in self.resolve_ref(
                        mod, class_name, f"self.{exact}"
                    )
                ]
            if prefix is not None:
                return [
                    (fi, True)
                    for fi in self.methods_with_prefix(
                        mod, class_name, prefix
                    )
                ]
            return []
        # tbl[k](...) / tbl.get(k[, default])(...)
        base: Optional[ast.AST] = None
        if isinstance(func, ast.Subscript):
            base = func.value
        elif (
            isinstance(func, ast.Call)
            and isinstance(func.func, ast.Attribute)
            and func.func.attr == "get"
        ):
            base = func.func.value
        if base is None:
            return []
        refs: Optional[Tuple[str, ...]] = None
        owner_mod, owner_class = mod, class_name
        if isinstance(base, ast.Name):
            if local_tables and base.id in local_tables:
                refs = local_tables[base.id]
            else:
                refs = mod.dispatch_tables.get(base.id)
        elif isinstance(base, ast.Attribute) and isinstance(
            base.value, ast.Name
        ):
            if base.value.id in ("self", "cls") and class_name is not None:
                ci = self.class_info(mod, class_name)
                classes = (
                    [ci, *self.ancestors(ci), *self.descendants(ci)]
                    if ci is not None else []
                )
                for c in classes:
                    refs = c.module.class_dispatch_tables.get(
                        (c.name, base.attr)
                    )
                    if refs is not None:
                        owner_mod, owner_class = c.module, c.name
                        break
                if refs is None and ci is None:
                    refs = mod.class_dispatch_tables.get(
                        (class_name, base.attr)
                    )
            else:
                target = mod.imports.get(base.value.id)
                tmod = self.by_name.get(target) if target else None
                if tmod is not None:
                    refs = tmod.dispatch_tables.get(base.attr)
                    owner_mod, owner_class = tmod, None
        if not refs:
            return []
        out: List[Tuple[FunctionInfo, bool]] = []
        seen: set = set()
        for ref in refs:
            via_self = ref.startswith(("self.", "cls."))
            for fi in self.resolve_ref(owner_mod, owner_class, ref):
                if fi.key not in seen:
                    seen.add(fi.key)
                    out.append((fi, via_self))
        return out
