"""Whole-program model for batonlint: modules, symbols, imports.

The per-file checkers see one ``ast.Module`` at a time; everything a
cross-module rule needs — "which function does ``secure.dh_shared_seed``
resolve to", "which class owns ``_register_lock``" — lives here.  A
:class:`Project` is built once per lint run from every file on the
command line, so project-scoped checkers (``ProjectChecker`` in the
engine) can follow calls across module boundaries.

Resolution is deliberately syntactic (no imports are executed, same
contract as the rest of batonlint):

* module names come from the filesystem when the file exists (walking
  up through ``__init__.py`` packages) and from the given path string
  for in-memory fixtures, so ``baton_tpu/server/fixture.py`` is module
  ``baton_tpu.server.fixture`` either way;
* ``import a.b as x`` / ``from a.b import f`` bind local aliases to
  dotted targets; relative imports resolve against the module's own
  package;
* a call resolves through (1) same-module functions/methods
  (``self.helper`` -> ``Class.helper``), (2) an imported symbol, or
  (3) ``alias.attr`` where the alias names a project module;
* since the class-hierarchy layer landed, ``self.method()`` also
  resolves through inheritance: the nearest definition up the base
  chain PLUS every override in known subclasses (class-hierarchy
  analysis — the receiver's dynamic type may be any subclass of the
  enclosing class), and ``super().method()`` resolves to the nearest
  base-class definition.  Re-exports and true dynamic dispatch
  (``getattr``, HOFs) remain out of scope — a resolver miss returns
  ``None``/``[]`` and the caller degrades to per-file behavior.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Tuple

from baton_tpu.analysis import _astutil as au

__all__ = ["ClassInfo", "FunctionInfo", "ModuleInfo", "Project"]


@dataclasses.dataclass
class FunctionInfo:
    """One def/async def, with enough context to name and revisit it."""

    qualname: str                 # "Class.method" or bare name
    class_name: Optional[str]
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"

    @property
    def key(self) -> str:
        """Project-unique id: ``module.dotted.name:Qual.name``."""
        return f"{self.module.name}:{self.qualname}"

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


@dataclasses.dataclass
class ClassInfo:
    """One class definition: enough to build the inheritance graph."""

    name: str                     # bare class name
    module: "ModuleInfo"
    node: ast.ClassDef
    base_names: Tuple[str, ...]   # raw dotted base expressions

    @property
    def key(self) -> str:
        """Project-unique id: ``module.dotted.name:ClassName``."""
        return f"{self.module.name}:{self.name}"

    def method(self, name: str) -> Optional["FunctionInfo"]:
        """The method defined ON this class (no inheritance walk)."""
        return self.module.functions.get(f"{self.name}.{name}")


class ModuleInfo:
    """One parsed source file plus its symbol table."""

    def __init__(
        self,
        path: str,
        source: str,
        tree: ast.Module,
        name: str,
        counter_registry: Optional[Tuple[frozenset, tuple]] = None,
    ) -> None:
        self.path = path
        self.posix_path = pathlib.PurePath(path).as_posix()
        self.parts = pathlib.PurePath(path).parts
        self.source = source
        self.tree = tree
        self.name = name
        self.counter_registry = counter_registry
        self.functions: Dict[str, FunctionInfo] = {}
        for qual, cls, node in au.iter_function_defs(tree):
            self.functions.setdefault(
                qual, FunctionInfo(qual, cls, node, self)
            )
        self.imports = _collect_imports(tree, name)
        self.classes: Dict[str, ClassInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases = tuple(
                    b for b in (au.dotted_name(base) for base in node.bases)
                    if b is not None
                )
                self.classes.setdefault(
                    node.name, ClassInfo(node.name, self, node, bases)
                )


def _module_name_for(path: str) -> str:
    """Dotted module name for ``path``.

    Real files walk up while ``__init__.py`` siblings exist, so
    ``/any/prefix/baton_tpu/server/x.py`` -> ``baton_tpu.server.x``.
    Nonexistent (fixture) paths fall back to the path string itself:
    ``fixtures/liba.py`` -> ``fixtures.liba``.
    """
    p = pathlib.Path(path)
    stem_parts: List[str] = [] if p.stem == "__init__" else [p.stem]
    if p.is_file():
        parent = p.resolve().parent
        parts = list(stem_parts)
        while (parent / "__init__.py").is_file():
            parts.insert(0, parent.name)
            parent = parent.parent
        return ".".join(parts) or p.stem
    pure = pathlib.PurePath(path)
    parts = [x for x in pure.parts[:-1] if x not in ("/", "\\", "..", ".")]
    return ".".join(parts + stem_parts) or p.stem


def _collect_imports(tree: ast.Module, module_name: str) -> Dict[str, str]:
    """``{local alias: dotted target}`` for every import in the module
    (function-level imports included — ``from . import secure`` inside a
    handler binds the same way for resolution purposes)."""
    imports: Dict[str, str] = {}
    pkg_parts = module_name.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    imports.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(
                    base_parts + ([node.module] if node.module else [])
                )
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{base}.{alias.name}" if base else alias.name
                imports[alias.asname or alias.name] = target
    return imports


class Project:
    """All modules of one lint run, indexed by path and dotted name."""

    def __init__(self) -> None:
        self.modules: List[ModuleInfo] = []
        self.by_path: Dict[str, ModuleInfo] = {}
        self.by_name: Dict[str, ModuleInfo] = {}
        self._hier: Optional[Tuple[Dict[str, List[str]],
                                   Dict[str, List[str]],
                                   Dict[str, ClassInfo]]] = None

    @classmethod
    def from_parsed(
        cls,
        entries: Iterable[Tuple[str, str, ast.Module,
                                Optional[Tuple[frozenset, tuple]]]],
    ) -> "Project":
        """Build from ``(path, source, tree, counter_registry)`` tuples
        (the engine parses; a file that failed to parse never gets
        here)."""
        project = cls()
        for path, source, tree, registry in entries:
            mod = ModuleInfo(path, source, tree, _module_name_for(path),
                             counter_registry=registry)
            project.modules.append(mod)
            project.by_path[path] = mod
            # first module wins on a name collision (e.g. two fixture
            # trees shipping an identically-named module)
            project.by_name.setdefault(mod.name, mod)
        return project

    def functions(self) -> Iterable[FunctionInfo]:
        for mod in self.modules:
            yield from mod.functions.values()

    def function_by_dotted(self, dotted: str) -> Optional[FunctionInfo]:
        """``baton_tpu.server.secure.dh_shared_seed`` -> FunctionInfo.

        Tries the longest module prefix first so ``pkg.mod.Class.method``
        resolves even when ``pkg.mod.Class`` isn't itself a module.
        """
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:i]))
            if mod is not None:
                hit = mod.functions.get(".".join(parts[i:]))
                if hit is not None:
                    return hit
        return None

    # -- class hierarchy (CHA) -----------------------------------------
    def _hierarchy(self):
        """``(parents, children, by_key)`` over every known class; built
        once per project, cycle-tolerant (a recursive base chain just
        stops unifying where the cycle closes)."""
        if self._hier is not None:
            return self._hier
        by_key: Dict[str, ClassInfo] = {}
        for mod in self.modules:
            for ci in mod.classes.values():
                by_key.setdefault(ci.key, ci)
        parents: Dict[str, List[str]] = {}
        children: Dict[str, List[str]] = {}
        for ci in by_key.values():
            for base in ci.base_names:
                parent = self._resolve_class_name(ci.module, base)
                if parent is None or parent.key == ci.key:
                    continue
                parents.setdefault(ci.key, []).append(parent.key)
                children.setdefault(parent.key, []).append(ci.key)
        self._hier = (parents, children, by_key)
        return self._hier

    def _class_by_dotted(self, dotted: str) -> Optional[ClassInfo]:
        parts = dotted.split(".")
        if len(parts) < 2:
            return None
        mod = self.by_name.get(".".join(parts[:-1]))
        if mod is None:
            return None
        return mod.classes.get(parts[-1])

    def _resolve_class_name(
        self, mod: ModuleInfo, dotted: str
    ) -> Optional[ClassInfo]:
        """A base-class expression (``Base``, ``pkg.Base``, imported
        alias) -> the ClassInfo it names, when it is a project class."""
        root, _, rest = dotted.partition(".")
        if not rest:
            ci = mod.classes.get(dotted)
            if ci is not None:
                return ci
            target = mod.imports.get(dotted)
            return self._class_by_dotted(target) if target else None
        target = mod.imports.get(root)
        if target is not None:
            return self._class_by_dotted(f"{target}.{rest}")
        return self._class_by_dotted(dotted)

    def class_info(
        self, mod: ModuleInfo, class_name: Optional[str]
    ) -> Optional[ClassInfo]:
        if class_name is None:
            return None
        return mod.classes.get(class_name)

    def ancestors(self, ci: ClassInfo) -> List[ClassInfo]:
        """Base classes of ``ci``, nearest first (BFS, cycle-safe)."""
        parents, _children, by_key = self._hierarchy()
        out: List[ClassInfo] = []
        seen = {ci.key}
        frontier = list(parents.get(ci.key, []))
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                if key in seen:
                    continue
                seen.add(key)
                out.append(by_key[key])
                nxt.extend(parents.get(key, []))
            frontier = nxt
        return out

    def descendants(self, ci: ClassInfo) -> List[ClassInfo]:
        """Known subclasses of ``ci``, transitively (BFS, cycle-safe)."""
        _parents, children, by_key = self._hierarchy()
        out: List[ClassInfo] = []
        seen = {ci.key}
        frontier = list(children.get(ci.key, []))
        while frontier:
            nxt: List[str] = []
            for key in frontier:
                if key in seen:
                    continue
                seen.add(key)
                out.append(by_key[key])
                nxt.extend(children.get(key, []))
            frontier = nxt
        return out

    def root_class_name(
        self, mod: ModuleInfo, class_name: Optional[str]
    ) -> Optional[str]:
        """Bare name of the topmost known ancestor of ``class_name`` —
        the namespace ``self.attr`` state and locks unify under, so a
        lock acquired in ``Sub`` and one in ``Base`` name the same
        object when ``Sub(Base)``."""
        if class_name is None:
            return None
        ci = self.class_info(mod, class_name)
        if ci is None:
            return class_name
        chain = self.ancestors(ci)
        return chain[-1].name if chain else ci.name

    def resolve_method(
        self, ci: ClassInfo, method: str
    ) -> Optional[FunctionInfo]:
        """Nearest definition of ``method`` on ``ci`` or up its bases."""
        hit = ci.method(method)
        if hit is not None:
            return hit
        for base in self.ancestors(ci):
            hit = base.method(method)
            if hit is not None:
                return hit
        return None

    def method_candidates(
        self, ci: ClassInfo, method: str
    ) -> List[FunctionInfo]:
        """CHA dispatch set for ``self.method()`` in class ``ci``: the
        nearest inherited definition plus every override in known
        subclasses (the receiver may be any subclass instance)."""
        out: List[FunctionInfo] = []
        seen: set = set()

        def add(fn: Optional[FunctionInfo]) -> None:
            if fn is not None and fn.key not in seen:
                seen.add(fn.key)
                out.append(fn)

        add(self.resolve_method(ci, method))
        for sub in self.descendants(ci):
            add(sub.method(method))
        return out

    # -- call resolution -----------------------------------------------
    def resolve_call_multi(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> List[FunctionInfo]:
        """Every function this call may statically dispatch to.

        ``self.method()``/``cls.method()`` resolve through the class
        hierarchy (nearest definition up the bases plus all subclass
        overrides); ``super().method()`` to the nearest base
        definition; everything else to at most one candidate via the
        module symbol table."""
        func = call.func
        ci = self.class_info(mod, class_name)
        # super().method(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Call)
            and isinstance(func.value.func, ast.Name)
            and func.value.func.id == "super"
        ):
            if ci is None:
                return []
            own = ci.method(func.attr)
            for base in self.ancestors(ci):
                hit = base.method(func.attr)
                if hit is not None and (own is None or hit.key != own.key):
                    return [hit]
            return []
        # self.method(...) / cls.method(...)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
        ):
            if ci is not None:
                hits = self.method_candidates(ci, func.attr)
                if hits:
                    return hits
            # no hierarchy info: fall through to the legacy single-shot
        single = self._resolve_call_single(mod, class_name, call)
        return [single] if single is not None else []

    def resolve_call(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> Optional[FunctionInfo]:
        """Best-effort single-target resolution (primary candidate —
        the nearest-MRO definition for ``self.`` calls)."""
        hits = self.resolve_call_multi(mod, class_name, call)
        return hits[0] if hits else None

    def _resolve_call_single(
        self,
        mod: ModuleInfo,
        class_name: Optional[str],
        call: ast.Call,
    ) -> Optional[FunctionInfo]:
        local = au.resolve_local_call(call, class_name)
        if local is not None:
            hit = mod.functions.get(local)
            if hit is not None:
                return hit
            if "." not in local:
                target = mod.imports.get(local)
                if target is not None:
                    return self.function_by_dotted(target)
            return None
        dotted = au.dotted_name(call.func)
        if dotted is None or "." not in dotted:
            return None
        root, rest = dotted.split(".", 1)
        target = self.imports_target(mod, root)
        if target is None:
            return None
        return self.function_by_dotted(f"{target}.{rest}")

    def imports_target(self, mod: ModuleInfo, alias: str) -> Optional[str]:
        return mod.imports.get(alias)
