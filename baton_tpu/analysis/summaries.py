"""Bottom-up fixpoint function summaries over the project call graph.

The per-function AST walks in the checkers see one hop; this module
sees the whole program.  For every function it computes a
:class:`FnSummary` — the blocking primitives it may execute, the locks
it may acquire, the locks it holds across an await, the network awaits
it may perform, which ``self.*`` attributes it reads and mutates, and
its parameter->return taint transfer — first locally (one shallow AST
walk per function, the part that is cacheable per file content hash),
then propagated bottom-up over the call graph: strongly connected
components are condensed (Tarjan) and processed in reverse topological
order, iterating each SCC's members to a fixpoint, so mutual recursion
converges and every rule built on summaries is genuinely multi-hop.

Propagation follows execution, not just reference: an edge from a
*sync* caller into an ``async def`` does not propagate effects (the
call merely builds a coroutine object), while async->async, async->sync
and sync->sync edges do.  ``self.*`` effect sets propagate only over
``self.``/``super()`` edges — a method called on some *other* object
mutates that object's state, not the caller's.

Every site a summary carries keeps the shortest witness call chain
(qualnames below the summarized function), so checkers can report the
path a hazard travels across modules, not just its endpoint.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.callgraph import CallEdge, CallGraph
from baton_tpu.analysis.project import ModuleInfo, Project

__all__ = [
    "BLOCKED_DOTTED",
    "BLOCKED_METHODS",
    "BLOCKED_MODULE_PREFIXES",
    "BLOCKED_NAMES",
    "FnSummary",
    "LocalFacts",
    "NETWORK_ATTRS",
    "NETWORK_DOTTED",
    "Site",
    "Summaries",
    "blocked_reason",
    "is_network_call",
    "lock_identity",
]

# -- blocking primitives (shared with BTL001) --------------------------
# fully-resolved dotted names that block the loop
BLOCKED_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop; await asyncio.sleep",
    "pickle.load": "pickle.load() is blocking CPU/IO work",
    "pickle.loads": "pickle.loads() is blocking CPU work",
    "jax.device_get": "jax.device_get() blocks on device transfer",
}
# any call into these modules blocks (compression is pure CPU burn)
BLOCKED_MODULE_PREFIXES = ("zlib.",)
# bare-name builtins
BLOCKED_NAMES = {"open": "open() is blocking file I/O"}
# method attributes that block regardless of receiver type
BLOCKED_METHODS = {
    "block_until_ready": ".block_until_ready() blocks on device compute",
    "read_text": "file I/O (.read_text) blocks the event loop",
    "write_text": "file I/O (.write_text) blocks the event loop",
    "read_bytes": "file I/O (.read_bytes) blocks the event loop",
    "write_bytes": "file I/O (.write_bytes) blocks the event loop",
}


def blocked_reason(call: ast.Call) -> Optional[Tuple[str, str]]:
    """``(display_name, reason)`` when the call is a blocking
    primitive, else None."""
    name = au.call_name(call)
    if name is not None:
        if name in BLOCKED_DOTTED:
            return name, BLOCKED_DOTTED[name]
        for prefix in BLOCKED_MODULE_PREFIXES:
            if name.startswith(prefix):
                return name, f"{prefix}* compression is blocking CPU work"
        if name in BLOCKED_NAMES:
            return name, BLOCKED_NAMES[name]
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in BLOCKED_METHODS:
        display = name if name is not None else f"<expr>.{func.attr}"
        return display, BLOCKED_METHODS[func.attr]
    return None


# -- network/queue await primitives (shared with BTL002) ---------------
# attribute names that mean "this await leaves the process" (HTTP verb,
# body read, queue hand-off) — receiver-agnostic by design: sessions,
# responses and queues go by many names
NETWORK_ATTRS = {
    "get", "post", "put", "patch", "delete", "head", "request",
    "read", "text", "json", "recv", "receive", "send", "send_json",
    "fetch", "connect", "join", "drain",
}
NETWORK_DOTTED = {"asyncio.sleep"}


def is_network_call(call: ast.Call) -> bool:
    dotted = au.call_name(call)
    if dotted in NETWORK_DOTTED:
        return True
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in NETWORK_ATTRS
    )


# -- lock identity -----------------------------------------------------
def lock_identity(
    expr_or_name,
    class_name: Optional[str],
    mod: ModuleInfo,
    project: Optional[Project] = None,
) -> Optional[str]:
    """Normalized project-wide lock identity for an ``async with``
    context expression (or its pre-extracted dotted name), or None when
    the context is not a lock.

    A "lock" is any context whose name ends with ``lock`` or ``mutex``
    — naming convention as lint contract.  Identities unify where
    references can: ``self._x_lock`` unifies under the ROOT class of
    the enclosing class's known inheritance chain (so the same
    attribute acquired in a base method and a subclass override is one
    lock), a module-global is ``pkg.mod.x_lock`` from its home module
    or through any import alias.  Locks reached through other objects'
    attributes stay module-local (no type inference)."""
    if isinstance(expr_or_name, str):
        name: Optional[str] = expr_or_name
    else:
        name = au.dotted_name(expr_or_name)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if not (leaf.endswith("lock") or leaf.endswith("mutex")):
        return None
    root, _, rest = name.partition(".")
    if root in ("self", "cls") and rest and class_name is not None:
        owner = class_name
        if project is not None:
            owner = project.root_class_name(mod, class_name) or class_name
        return f"{owner}.{rest}"
    if rest:
        target = mod.imports.get(root)
        if target is not None:
            # module-global lock referenced through an import alias:
            # unify with its home-module bare name
            return f"{target}.{rest}"
        return f"{mod.name}:{name}"  # some other object's attribute
    return f"{mod.name}.{name}"


# -- self.* attribute access extraction --------------------------------
_SELF_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "set",
}


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """``self.A``/``cls.A`` (possibly deeper: ``self.A.b``) -> ``A``."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            return node.attr
        node = node.value
    return None


# -- local facts (cacheable) -------------------------------------------
Site = Tuple[int, int]  # (line, col) within the function's own module


@dataclasses.dataclass
class LocalFacts:
    """Per-function facts derived ONLY from that function's AST —
    content-addressable, hence what ``.batonlint_cache.json`` stores."""

    qual: str
    class_name: Optional[str]
    is_async: bool
    has_await: bool
    # ((line, col, display, reason), ...)
    blocking: Tuple[Tuple[int, int, str, str], ...] = ()
    # ((raw_dotted, line), ...) raw lock exprs from `async with`
    acquires_raw: Tuple[Tuple[str, int], ...] = ()
    # raw lock exprs held lexically at >=1 await
    awaits_held_raw: Tuple[str, ...] = ()
    # ((line, col, display), ...) awaited network/queue primitives
    network_awaits: Tuple[Tuple[int, int, str], ...] = ()
    # ((line, col, (raw_locks...)), ...) locks held at each call site
    held_at_call: Tuple[Tuple[int, int, Tuple[str, ...]], ...] = ()
    self_reads: Tuple[str, ...] = ()
    self_writes: Tuple[str, ...] = ()
    # ((needs_taint, kind, line, col, message), ...) host ops that are
    # hazards when this function executes under a jit/shard_map trace
    taint_ops: Tuple[Tuple[bool, str, int, int, str], ...] = ()
    returns_param_taint: bool = False

    def to_json(self) -> dict:
        return {
            "qual": self.qual,
            "class_name": self.class_name,
            "is_async": self.is_async,
            "has_await": self.has_await,
            "blocking": [list(x) for x in self.blocking],
            "acquires_raw": [list(x) for x in self.acquires_raw],
            "awaits_held_raw": list(self.awaits_held_raw),
            "network_awaits": [list(x) for x in self.network_awaits],
            "held_at_call": [
                [line, col, list(locks)]
                for line, col, locks in self.held_at_call
            ],
            "self_reads": list(self.self_reads),
            "self_writes": list(self.self_writes),
            "taint_ops": [list(x) for x in self.taint_ops],
            "returns_param_taint": self.returns_param_taint,
        }

    @classmethod
    def from_json(cls, data: dict) -> "LocalFacts":
        return cls(
            qual=data["qual"],
            class_name=data.get("class_name"),
            is_async=bool(data["is_async"]),
            has_await=bool(data["has_await"]),
            blocking=tuple(
                (int(a), int(b), str(c), str(d))
                for a, b, c, d in data.get("blocking", [])
            ),
            acquires_raw=tuple(
                (str(a), int(b)) for a, b in data.get("acquires_raw", [])
            ),
            awaits_held_raw=tuple(
                str(x) for x in data.get("awaits_held_raw", [])
            ),
            network_awaits=tuple(
                (int(a), int(b), str(c))
                for a, b, c in data.get("network_awaits", [])
            ),
            held_at_call=tuple(
                (int(line), int(col), tuple(str(x) for x in locks))
                for line, col, locks in data.get("held_at_call", [])
            ),
            self_reads=tuple(str(x) for x in data.get("self_reads", [])),
            self_writes=tuple(str(x) for x in data.get("self_writes", [])),
            taint_ops=tuple(
                (bool(a), str(b), int(c), int(d), str(e))
                for a, b, c, d, e in data.get("taint_ops", [])
            ),
            returns_param_taint=bool(data.get("returns_param_taint", False)),
        )


_SUSPENDERS = (ast.Await, ast.AsyncFor, ast.AsyncWith)


def compute_local_facts(mod: ModuleInfo) -> Dict[str, LocalFacts]:
    """``{qualname: LocalFacts}`` for every function in the module."""
    out: Dict[str, LocalFacts] = {}
    for fn_info in mod.functions.values():
        out[fn_info.qualname] = _local_facts_for(fn_info)
    return out


def _local_facts_for(fn_info) -> LocalFacts:
    node = fn_info.node
    is_async = isinstance(node, ast.AsyncFunctionDef)
    blocking: List[Tuple[int, int, str, str]] = []
    acquires_raw: List[Tuple[str, int]] = []
    awaits_held_raw: set = set()
    network_awaits: List[Tuple[int, int, str]] = []
    held_at_call: List[Tuple[int, int, Tuple[str, ...]]] = []
    self_reads: set = set()
    self_writes: set = set()
    has_await = False

    def is_lock_name(name: Optional[str]) -> bool:
        if name is None:
            return False
        leaf = name.rsplit(".", 1)[-1].lower()
        return leaf.endswith("lock") or leaf.endswith("mutex")

    def visit(n: ast.AST, held: Tuple[str, ...]) -> None:
        nonlocal has_await
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return  # separate execution context (to_thread closures)
        if isinstance(n, _SUSPENDERS):
            has_await = True
            awaits_held_raw.update(held)
        if isinstance(n, ast.AsyncWith):
            new_held = held
            header = [i.context_expr for i in n.items]
            for item in n.items:
                expr = item.context_expr
                raw = au.dotted_name(expr)
                if is_lock_name(raw):
                    acquires_raw.append((raw, n.lineno))
                    new_held = new_held + (raw,)
                elif isinstance(expr, ast.Call):
                    if is_network_call(expr):
                        network_awaits.append(
                            (expr.lineno, expr.col_offset,
                             au.call_name(expr)
                             or f"<expr>.{expr.func.attr}")
                        )
                    held_at_call.append(
                        (expr.lineno, expr.col_offset, held)
                    )
                    for child in ast.iter_child_nodes(expr):
                        visit(child, held)
            for child in ast.iter_child_nodes(n):
                if child in header or isinstance(child, ast.withitem):
                    continue
                visit(child, new_held)
            return
        if isinstance(n, ast.Await) and isinstance(n.value, ast.Call):
            if is_network_call(n.value):
                network_awaits.append(
                    (n.value.lineno, n.value.col_offset,
                     au.call_name(n.value)
                     or f"<expr>.{n.value.func.attr}")
                )
        if isinstance(n, ast.Call):
            reason = blocked_reason(n)
            if reason is not None:
                blocking.append(
                    (n.lineno, n.col_offset, reason[0], reason[1])
                )
            held_at_call.append((n.lineno, n.col_offset, held))
        if isinstance(n, ast.Attribute):
            attr = (
                n.attr
                if isinstance(n.value, ast.Name)
                and n.value.id in ("self", "cls")
                else None
            )
            if attr is not None:
                if isinstance(n.ctx, (ast.Store, ast.Del)):
                    self_writes.add(attr)
                else:
                    self_reads.add(attr)
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    attr = _self_attr_of(t)
                    if attr is not None:
                        self_writes.add(attr)
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _SELF_MUTATORS
        ):
            attr = _self_attr_of(n.func.value)
            if attr is not None:
                self_writes.add(attr)
        for child in ast.iter_child_nodes(n):
            visit(child, held)

    for stmt in node.body:
        visit(stmt, ())

    taint_ops, returns_taint = _local_taint_facts(node)
    return LocalFacts(
        qual=fn_info.qualname,
        class_name=fn_info.class_name,
        is_async=is_async,
        has_await=has_await,
        blocking=tuple(blocking),
        acquires_raw=tuple(acquires_raw),
        awaits_held_raw=tuple(sorted(awaits_held_raw)),
        network_awaits=tuple(network_awaits),
        held_at_call=tuple(held_at_call),
        self_reads=tuple(sorted(self_reads)),
        self_writes=tuple(sorted(self_writes)),
        taint_ops=taint_ops,
        returns_param_taint=returns_taint,
    )


def _local_taint_facts(node) -> Tuple[tuple, bool]:
    """Host-side ops in this function that become hazards under a JAX
    trace, plus whether the return value derives from the parameters.

    ``needs_taint`` ops (casts, np materializers, ``.item()``) fire
    only when the function is CALLED with traced arguments; ``print``
    is a hazard in any traced execution (it runs at trace time only)."""
    tainted = au.param_names(node) - {"self", "cls"}
    body = node.body if isinstance(node.body, list) else [node.body]
    oracle = au.make_taint_oracle(tainted)
    for _ in range(10):
        if not au.propagate_taint(body, tainted, oracle):
            break

    ops: List[Tuple[bool, str, int, int, str]] = []
    returns_taint = False
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Return) and n.value is not None:
                if oracle(n.value):
                    returns_taint = True
            if not isinstance(n, ast.Call):
                continue
            name = au.call_name(n)
            if name == "print":
                ops.append(
                    (False, "print", n.lineno, n.col_offset,
                     "print() runs at trace time only; use "
                     "jax.debug.print for per-call output")
                )
            elif (
                name in ("float", "int", "bool", "complex")
                and n.args
                and oracle(n.args[0])
            ):
                ops.append(
                    (True, "cast", n.lineno, n.col_offset,
                     f"{name}() on a value derived from the caller's "
                     f"traced arguments concretizes the tracer")
                )
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in ("asarray", "array", "copy")
                and au.dotted_name(n.func.value) in ("np", "numpy")
                and n.args
                and oracle(n.args[0])
            ):
                ops.append(
                    (True, "materialize", n.lineno, n.col_offset,
                     f"np.{n.func.attr}() on a value derived from the "
                     f"caller's traced arguments materializes the "
                     f"tracer on host; use jnp.{n.func.attr}")
                )
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "item"
                and not n.args and not n.keywords
                and oracle(n.func.value)
            ):
                ops.append(
                    (True, "item", n.lineno, n.col_offset,
                     ".item() on a value derived from the caller's "
                     "traced arguments blocks on a device->host "
                     "transfer per trace")
                )
    return tuple(ops), returns_taint


# -- fixpoint summaries ------------------------------------------------
@dataclasses.dataclass
class FnSummary:
    """What one function may do, including everything reachable through
    its resolved calls.  Site dicts map ``(path, line, col)`` to a
    payload whose last element is the witness chain (qualnames below
    this function, shortest first discovered)."""

    key: str
    qualname: str
    is_async: bool
    has_await: bool                     # this frame itself suspends
    may_suspend: bool                   # suspends here or in a callee
    # (path, line, col) -> (display, reason, chain)
    blocking: Dict[tuple, tuple] = dataclasses.field(default_factory=dict)
    # (path, line, col) -> (display, chain)
    network_awaits: Dict[tuple, tuple] = dataclasses.field(
        default_factory=dict
    )
    acquires: FrozenSet[str] = frozenset()
    awaits_held: FrozenSet[str] = frozenset()
    self_reads: FrozenSet[str] = frozenset()
    self_writes: FrozenSet[str] = frozenset()
    # (path, line, col) -> (needs_taint, kind, message, chain)
    taint_ops: Dict[tuple, tuple] = dataclasses.field(default_factory=dict)
    returns_param_taint: bool = False


def _tarjan_sccs(
    keys: Sequence[str], succ: Dict[str, List[str]]
) -> List[List[str]]:
    """Iterative Tarjan: SCCs in reverse topological order (every
    successor SCC appears before its callers)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: set = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in keys:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            children = succ.get(node, [])
            for i in range(pi, len(children)):
                child = children[i]
                if child not in index:
                    work[-1] = (node, i + 1)
                    work.append((child, 0))
                    recursed = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if recursed:
                continue
            work.pop()
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


class Summaries:
    """Fixpoint summaries for every function of a project.

    ``cached_locals`` maps module path -> ``{qual: LocalFacts}`` for
    files whose content hash matched the incremental cache; those
    modules skip the local extraction walk entirely (the fixpoint
    always reruns — it is global and cheap next to parsing)."""

    def __init__(
        self,
        project: Project,
        graph: Optional[CallGraph] = None,
        cached_locals: Optional[Dict[str, Dict[str, LocalFacts]]] = None,
    ) -> None:
        self.project = project
        self.graph = graph if graph is not None else CallGraph(project)
        self.locals: Dict[str, LocalFacts] = {}
        self.local_facts_by_path: Dict[str, Dict[str, LocalFacts]] = {}
        self.cache_hits: List[str] = []
        self.cache_misses: List[str] = []
        cached_locals = cached_locals or {}
        for mod in project.modules:
            cached = cached_locals.get(mod.path)
            if cached is not None and set(cached) == set(
                fi.qualname for fi in mod.functions.values()
            ):
                facts = cached
                self.cache_hits.append(mod.path)
            else:
                facts = compute_local_facts(mod)
                self.cache_misses.append(mod.path)
            self.local_facts_by_path[mod.path] = facts
            for fi in mod.functions.values():
                lf = facts.get(fi.qualname)
                if lf is not None:
                    self.locals[fi.key] = lf
        self.by_key: Dict[str, FnSummary] = {}
        self._compute()

    def get(self, key: str) -> Optional[FnSummary]:
        return self.by_key.get(key)

    def for_function(self, fn_info) -> Optional[FnSummary]:
        return self.by_key.get(fn_info.key)

    # ------------------------------------------------------------------
    def _compute(self) -> None:
        project = self.project
        graph = self.graph

        # seed every function from its local facts
        for fn in project.functions():
            lf = self.locals.get(fn.key)
            if lf is None:
                continue
            mod = fn.module
            acquires = frozenset(
                x for x in (
                    lock_identity(raw, fn.class_name, mod, project)
                    for raw, _line in lf.acquires_raw
                ) if x is not None
            )
            awaits_held = frozenset(
                x for x in (
                    lock_identity(raw, fn.class_name, mod, project)
                    for raw in lf.awaits_held_raw
                ) if x is not None
            )
            summ = FnSummary(
                key=fn.key,
                qualname=fn.qualname,
                is_async=lf.is_async,
                has_await=lf.has_await,
                may_suspend=lf.has_await,
                acquires=acquires,
                awaits_held=awaits_held,
                self_reads=frozenset(lf.self_reads),
                self_writes=frozenset(lf.self_writes),
                returns_param_taint=lf.returns_param_taint,
            )
            for line, col, display, reason in lf.blocking:
                summ.blocking[(mod.path, line, col)] = (display, reason, ())
            for line, col, display in lf.network_awaits:
                summ.network_awaits[(mod.path, line, col)] = (display, ())
            for needs, kind, line, col, msg in lf.taint_ops:
                summ.taint_ops[(mod.path, line, col)] = (
                    needs, kind, msg, ()
                )
            self.by_key[fn.key] = summ

        # held locks at each call site, normalized, for awaits_held
        held_at: Dict[str, Dict[tuple, FrozenSet[str]]] = {}
        for fn in project.functions():
            lf = self.locals.get(fn.key)
            if lf is None:
                continue
            per_site: Dict[tuple, FrozenSet[str]] = {}
            for line, col, raw_locks in lf.held_at_call:
                per_site[(line, col)] = frozenset(
                    x for x in (
                        lock_identity(r, fn.class_name, fn.module, project)
                        for r in raw_locks
                    ) if x is not None
                )
            held_at[fn.key] = per_site

        succ: Dict[str, List[str]] = {
            key: sorted({e.callee.key for e in edges})
            for key, edges in graph.edges.items()
        }
        sccs = _tarjan_sccs(sorted(self.by_key), succ)

        for scc in sccs:
            # members of one SCC iterate together until stable; a
            # singleton without a self-loop stabilizes in one pass
            for _ in range(len(scc) * 2 + 1):
                changed = False
                for key in scc:
                    if self._merge_callees(key, held_at):
                        changed = True
                if not changed:
                    break

    def _merge_callees(
        self, key: str, held_at: Dict[str, Dict[tuple, FrozenSet[str]]]
    ) -> bool:
        summ = self.by_key.get(key)
        if summ is None:
            return False
        changed = False
        for edge in self.graph.callees(key):
            callee = self.by_key.get(edge.callee.key)
            if callee is None:
                continue
            # a sync frame calling an async def only builds a coroutine
            # object — nothing in the callee executes at this site
            if callee.is_async and not summ.is_async:
                continue
            chain_step = (edge.callee.qualname,)
            for site, (display, reason, chain) in callee.blocking.items():
                if site not in summ.blocking:
                    summ.blocking[site] = (
                        display, reason, chain_step + chain
                    )
                    changed = True
            for site, (display, chain) in callee.network_awaits.items():
                if site not in summ.network_awaits:
                    summ.network_awaits[site] = (
                        display, chain_step + chain
                    )
                    changed = True
            if not callee.acquires <= summ.acquires:
                summ.acquires = summ.acquires | callee.acquires
                changed = True
            new_awaits_held = callee.awaits_held
            if callee.may_suspend:
                site_held = held_at.get(key, {}).get(
                    (edge.node.lineno, edge.node.col_offset)
                )
                if site_held:
                    new_awaits_held = new_awaits_held | site_held
                if not summ.may_suspend:
                    summ.may_suspend = True
                    changed = True
            if not new_awaits_held <= summ.awaits_held:
                summ.awaits_held = summ.awaits_held | new_awaits_held
                changed = True
            if edge.via_self:
                if not callee.self_reads <= summ.self_reads:
                    summ.self_reads = summ.self_reads | callee.self_reads
                    changed = True
                if not callee.self_writes <= summ.self_writes:
                    summ.self_writes = (
                        summ.self_writes | callee.self_writes
                    )
                    changed = True
            for site, (needs, kind, msg, chain) in callee.taint_ops.items():
                if site not in summ.taint_ops:
                    summ.taint_ops[site] = (
                        needs, kind, msg, chain_step + chain
                    )
                    changed = True
        return changed


def get_summaries(project: Project) -> Summaries:
    """Per-run memoized summaries: checkers share one fixpoint pass."""
    summ = getattr(project, "_summaries", None)
    if summ is None:
        cached = getattr(project, "_cached_local_facts", None)
        summ = Summaries(project, cached_locals=cached)
        project._summaries = summ
    return summ
