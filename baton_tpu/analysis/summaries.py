"""Bottom-up fixpoint function summaries over the project call graph.

The per-function AST walks in the checkers see one hop; this module
sees the whole program.  For every function it computes a
:class:`FnSummary` — the blocking primitives it may execute, the locks
it may acquire, the locks it holds across an await, the network awaits
it may perform, which ``self.*`` attributes it reads and mutates, and
its parameter->return taint transfer — first locally (one shallow AST
walk per function, the part that is cacheable per file content hash),
then propagated bottom-up over the call graph: strongly connected
components are condensed (Tarjan) and processed in reverse topological
order, iterating each SCC's members to a fixpoint, so mutual recursion
converges and every rule built on summaries is genuinely multi-hop.

Propagation follows execution, not just reference: an edge from a
*sync* caller into an ``async def`` does not propagate effects (the
call merely builds a coroutine object), while async->async, async->sync
and sync->sync edges do.  ``self.*`` effect sets propagate only over
``self.``/``super()`` edges — a method called on some *other* object
mutates that object's state, not the caller's.

Every site a summary carries keeps the shortest witness call chain
(qualnames below the summarized function), so checkers can report the
path a hazard travels across modules, not just its endpoint.

On top of the per-function facts this module roots the call graph at
real runtime *entry points* (route registrations, ``PeriodicTask`` and
loop callbacks, ``to_thread``/executor/``threading.Thread`` dispatch)
and propagates an execution-context lattice — ``loop``, ``thread``, or
both — along execution edges (:class:`CtxWitness`).  Context-sensitive
rules (BTL001/BTL005/BTL006/BTL007) ask :meth:`Summaries.context_kinds`
which worlds a function can run in, with a witness chain back to the
registration site for the diagnostic.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from baton_tpu.analysis import _astutil as au
from baton_tpu.analysis.callgraph import CallEdge, CallGraph
from baton_tpu.analysis.project import ModuleInfo, Project

__all__ = [
    "BLOCKED_DOTTED",
    "BLOCKED_METHODS",
    "BLOCKED_MODULE_PREFIXES",
    "BLOCKED_NAMES",
    "CtxWitness",
    "FnSummary",
    "LocalFacts",
    "NETWORK_ATTRS",
    "NETWORK_DOTTED",
    "Site",
    "Summaries",
    "blocked_reason",
    "is_network_call",
    "lock_identity",
]

# -- blocking primitives (shared with BTL001) --------------------------
# fully-resolved dotted names that block the loop
BLOCKED_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop; await asyncio.sleep",
    "pickle.load": "pickle.load() is blocking CPU/IO work",
    "pickle.loads": "pickle.loads() is blocking CPU work",
    "jax.device_get": "jax.device_get() blocks on device transfer",
}
# any call into these modules blocks (compression is pure CPU burn)
BLOCKED_MODULE_PREFIXES = ("zlib.",)
# bare-name builtins
BLOCKED_NAMES = {"open": "open() is blocking file I/O"}
# method attributes that block regardless of receiver type
BLOCKED_METHODS = {
    "block_until_ready": ".block_until_ready() blocks on device compute",
    "read_text": "file I/O (.read_text) blocks the event loop",
    "write_text": "file I/O (.write_text) blocks the event loop",
    "read_bytes": "file I/O (.read_bytes) blocks the event loop",
    "write_bytes": "file I/O (.write_bytes) blocks the event loop",
}


def blocked_reason(call: ast.Call) -> Optional[Tuple[str, str]]:
    """``(display_name, reason)`` when the call is a blocking
    primitive, else None."""
    name = au.call_name(call)
    if name is not None:
        if name in BLOCKED_DOTTED:
            return name, BLOCKED_DOTTED[name]
        for prefix in BLOCKED_MODULE_PREFIXES:
            if name.startswith(prefix):
                return name, f"{prefix}* compression is blocking CPU work"
        if name in BLOCKED_NAMES:
            return name, BLOCKED_NAMES[name]
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in BLOCKED_METHODS:
        display = name if name is not None else f"<expr>.{func.attr}"
        return display, BLOCKED_METHODS[func.attr]
    return None


# -- network/queue await primitives (shared with BTL002) ---------------
# attribute names that mean "this await leaves the process" (HTTP verb,
# body read, queue hand-off) — receiver-agnostic by design: sessions,
# responses and queues go by many names
NETWORK_ATTRS = {
    "get", "post", "put", "patch", "delete", "head", "request",
    "read", "text", "json", "recv", "receive", "send", "send_json",
    "fetch", "connect", "join", "drain",
}
NETWORK_DOTTED = {"asyncio.sleep"}


def is_network_call(call: ast.Call) -> bool:
    dotted = au.call_name(call)
    if dotted in NETWORK_DOTTED:
        return True
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in NETWORK_ATTRS
    )


# -- lock identity -----------------------------------------------------
def lock_identity(
    expr_or_name,
    class_name: Optional[str],
    mod: ModuleInfo,
    project: Optional[Project] = None,
) -> Optional[str]:
    """Normalized project-wide lock identity for an ``async with``
    context expression (or its pre-extracted dotted name), or None when
    the context is not a lock.

    A "lock" is any context whose name ends with ``lock`` or ``mutex``
    — naming convention as lint contract.  Identities unify where
    references can: ``self._x_lock`` unifies under the ROOT class of
    the enclosing class's known inheritance chain (so the same
    attribute acquired in a base method and a subclass override is one
    lock), a module-global is ``pkg.mod.x_lock`` from its home module
    or through any import alias.  Locks reached through other objects'
    attributes stay module-local (no type inference)."""
    if isinstance(expr_or_name, str):
        name: Optional[str] = expr_or_name
    else:
        name = au.dotted_name(expr_or_name)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1].lower()
    if not (leaf.endswith("lock") or leaf.endswith("mutex")):
        return None
    root, _, rest = name.partition(".")
    if root in ("self", "cls") and rest and class_name is not None:
        owner = class_name
        if project is not None:
            owner = project.root_class_name(mod, class_name) or class_name
        return f"{owner}.{rest}"
    if rest:
        target = mod.imports.get(root)
        if target is not None:
            # module-global lock referenced through an import alias:
            # unify with its home-module bare name
            return f"{target}.{rest}"
        return f"{mod.name}:{name}"  # some other object's attribute
    return f"{mod.name}.{name}"


# -- self.* attribute access extraction --------------------------------
_SELF_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "set",
}

# -- execution-context registration tables ------------------------------
# aiohttp route table: method attr -> positional index of the handler
_ROUTE_REGISTRARS = {
    "add_get": 1, "add_post": 1, "add_put": 1, "add_patch": 1,
    "add_delete": 1, "add_head": 1, "add_route": 2,
}
# loop-callback registrars: the referenced callable runs ON the loop
_LOOP_CB_REGISTRARS = {
    "call_soon": 0, "call_soon_threadsafe": 0, "add_done_callback": 0,
    "call_later": 1, "call_at": 1,
}
# thread dispatchers: the referenced callable runs OFF the loop
_THREAD_REGISTRARS = {
    "to_thread": 0,
    "submit": 0,
    "run_in_executor": 1,
    # the ingest pipeline's own executor API (server/ingest.py): both
    # hand the callable to a ThreadPoolExecutor lane
    "submit_decode": 0,
    "submit_fold": 1,
}

# asyncio primitives a `self.X = asyncio.Y()` assignment declares
_ASYNCIO_FACTORIES = {
    "Lock", "Event", "Condition", "Semaphore", "BoundedSemaphore",
    "Queue", "LifoQueue", "PriorityQueue", "Future",
}
# non-threadsafe methods of those primitives (their sync APIs — the
# ones a worker thread CAN call, incorrectly; awaited APIs need a loop)
_ASYNCIO_TOUCH_METHODS = {
    "set", "clear", "put_nowait", "get_nowait", "set_result",
    "set_exception", "release", "notify", "notify_all",
}
# loop-affine methods regardless of receiver attr bookkeeping
_LOOP_AFFINE_METHODS = {"call_soon", "call_later", "call_at", "create_task"}


def _callable_ref(expr: ast.AST) -> Optional[str]:
    """A callable *reference* expression -> raw dotted ref string
    (``functools.partial(f, ...)`` unwraps to ``f``)."""
    d = au.dotted_name(expr)
    if d is not None:
        return d
    if isinstance(expr, ast.Call):
        cn = au.call_name(expr)
        if cn is not None and cn.rsplit(".", 1)[-1] == "partial" and expr.args:
            return _callable_ref(expr.args[0])
    return None


def _alias_envs(tree: ast.Module) -> Dict[int, Dict[str, str]]:
    """Per function node (by ``id``): local names that are stable
    aliases of a bare ``self.X`` read (``r = self._round``), with the
    enclosing function's aliases inherited by nested defs — the channel
    through which a fold-lane closure mutates instance state.  A name
    also bound to anything else anywhere in the function is ambiguous
    and dropped."""
    envs: Dict[int, Dict[str, str]] = {}

    def own_bindings(fn) -> Tuple[Dict[str, str], set]:
        aliases: Dict[str, str] = {}
        shadowed: set = set(au.param_names(fn))
        for n in au.walk_shallow(fn):
            if isinstance(n, ast.Assign):
                src = None
                v = n.value
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id in ("self", "cls")
                ):
                    src = v.attr
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        if src is not None and t.id not in shadowed:
                            if aliases.get(t.id, src) != src:
                                shadowed.add(t.id)
                            else:
                                aliases[t.id] = src
                        else:
                            shadowed.add(t.id)
                    else:
                        # tuple/list unpacking rebinds its Store names;
                        # a store THROUGH the name (r.x[k] = v) does not
                        # rebind r itself
                        for e in ast.walk(t):
                            if isinstance(e, ast.Name) and isinstance(
                                e.ctx, ast.Store
                            ):
                                shadowed.add(e.id)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(n.target, ast.Name):
                    shadowed.add(n.target.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                for e in ast.walk(n.target):
                    if isinstance(e, ast.Name):
                        shadowed.add(e.id)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None:
                        for e in ast.walk(item.optional_vars):
                            if isinstance(e, ast.Name):
                                shadowed.add(e.id)
            elif isinstance(n, ast.NamedExpr):
                if isinstance(n.target, ast.Name):
                    shadowed.add(n.target.id)
        return (
            {k: v for k, v in aliases.items() if k not in shadowed},
            shadowed,
        )

    def walk(node: ast.AST, inherited: Dict[str, str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                own, shadowed = own_bindings(child)
                env = {
                    k: v for k, v in inherited.items()
                    if k not in shadowed and k not in own
                }
                env.update(own)
                envs[id(child)] = env
                walk(child, env)
            elif isinstance(child, ast.ClassDef):
                walk(child, {})
            else:
                walk(child, inherited)

    walk(tree, {})
    return envs


def _scope_names(fn) -> Tuple[set, set, set]:
    """``(store_locals, all_locals, global_decls)`` for one function:
    names bound by Name-store/params, the same plus nested-def names,
    and names declared ``global``."""
    gdecl: set = set()
    store_locals: set = set(au.param_names(fn))
    def_names: set = set()
    for n in au.walk_shallow(fn):
        if isinstance(n, ast.Global):
            gdecl.update(n.names)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            def_names.add(n.name)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            store_locals.add(n.id)
    store_locals -= gdecl
    return store_locals, store_locals | def_names, gdecl


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """``self.A``/``cls.A`` (possibly deeper: ``self.A.b``) -> ``A``."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            return node.attr
        node = node.value
    return None


# -- local facts (cacheable) -------------------------------------------
Site = Tuple[int, int]  # (line, col) within the function's own module


@dataclasses.dataclass
class LocalFacts:
    """Per-function facts derived ONLY from that function's AST —
    content-addressable, hence what ``.batonlint_cache.json`` stores."""

    qual: str
    class_name: Optional[str]
    is_async: bool
    has_await: bool
    # ((line, col, display, reason), ...)
    blocking: Tuple[Tuple[int, int, str, str], ...] = ()
    # ((raw_dotted, line), ...) raw lock exprs from `async with`
    acquires_raw: Tuple[Tuple[str, int], ...] = ()
    # raw lock exprs held lexically at >=1 await
    awaits_held_raw: Tuple[str, ...] = ()
    # ((line, col, display), ...) awaited network/queue primitives
    network_awaits: Tuple[Tuple[int, int, str], ...] = ()
    # ((line, col, (raw_locks...)), ...) locks held at each call site
    held_at_call: Tuple[Tuple[int, int, Tuple[str, ...]], ...] = ()
    self_reads: Tuple[str, ...] = ()
    self_writes: Tuple[str, ...] = ()
    # ((needs_taint, kind, line, col, message), ...) host ops that are
    # hazards when this function executes under a jit/shard_map trace
    taint_ops: Tuple[Tuple[bool, str, int, int, str], ...] = ()
    returns_param_taint: bool = False
    # -- execution-context facts (also per-file, also cacheable) -------
    # ((attr, line, col, is_write, (sync_locks...), (async_locks...)),
    #  ...) instance-attribute accesses incl. through self-aliases
    attr_accesses: Tuple[
        Tuple[str, int, int, bool, Tuple[str, ...], Tuple[str, ...]], ...
    ] = ()
    # ((name, line, col, is_write, (sync_locks...)), ...) module-global
    # accesses (reads of module names, `global`-declared / container
    # mutation writes)
    global_accesses: Tuple[
        Tuple[str, int, int, bool, Tuple[str, ...]], ...
    ] = ()
    # self.X attrs assigned an asyncio primitive in this function
    asyncio_defs: Tuple[str, ...] = ()
    # ((attr_or_recv, line, col, method), ...) non-threadsafe asyncio
    # API touches ("<loop>" recv for call_soon/create_task et al.)
    asyncio_touches: Tuple[Tuple[str, int, int, str], ...] = ()
    # ((kind, ref, line), ...) entry-point registrations made HERE:
    # kind in {"route", "loop_cb", "thread"}, ref is the raw callable
    entry_regs: Tuple[Tuple[str, str, int], ...] = ()
    # bare/dotted names referenced outside call position (callbacks
    # passed by value) — dead-code roots
    name_refs: Tuple[str, ...] = ()

    def to_json(self) -> dict:
        return {
            "qual": self.qual,
            "class_name": self.class_name,
            "is_async": self.is_async,
            "has_await": self.has_await,
            "blocking": [list(x) for x in self.blocking],
            "acquires_raw": [list(x) for x in self.acquires_raw],
            "awaits_held_raw": list(self.awaits_held_raw),
            "network_awaits": [list(x) for x in self.network_awaits],
            "held_at_call": [
                [line, col, list(locks)]
                for line, col, locks in self.held_at_call
            ],
            "self_reads": list(self.self_reads),
            "self_writes": list(self.self_writes),
            "taint_ops": [list(x) for x in self.taint_ops],
            "returns_param_taint": self.returns_param_taint,
            "attr_accesses": [
                [a, ln, c, w, list(s), list(al)]
                for a, ln, c, w, s, al in self.attr_accesses
            ],
            "global_accesses": [
                [a, ln, c, w, list(s)]
                for a, ln, c, w, s in self.global_accesses
            ],
            "asyncio_defs": list(self.asyncio_defs),
            "asyncio_touches": [list(x) for x in self.asyncio_touches],
            "entry_regs": [list(x) for x in self.entry_regs],
            "name_refs": list(self.name_refs),
        }

    @classmethod
    def from_json(cls, data: dict) -> "LocalFacts":
        return cls(
            qual=data["qual"],
            class_name=data.get("class_name"),
            is_async=bool(data["is_async"]),
            has_await=bool(data["has_await"]),
            blocking=tuple(
                (int(a), int(b), str(c), str(d))
                for a, b, c, d in data.get("blocking", [])
            ),
            acquires_raw=tuple(
                (str(a), int(b)) for a, b in data.get("acquires_raw", [])
            ),
            awaits_held_raw=tuple(
                str(x) for x in data.get("awaits_held_raw", [])
            ),
            network_awaits=tuple(
                (int(a), int(b), str(c))
                for a, b, c in data.get("network_awaits", [])
            ),
            held_at_call=tuple(
                (int(line), int(col), tuple(str(x) for x in locks))
                for line, col, locks in data.get("held_at_call", [])
            ),
            self_reads=tuple(str(x) for x in data.get("self_reads", [])),
            self_writes=tuple(str(x) for x in data.get("self_writes", [])),
            taint_ops=tuple(
                (bool(a), str(b), int(c), int(d), str(e))
                for a, b, c, d, e in data.get("taint_ops", [])
            ),
            returns_param_taint=bool(data.get("returns_param_taint", False)),
            attr_accesses=tuple(
                (str(a), int(ln), int(c), bool(w),
                 tuple(str(x) for x in s), tuple(str(x) for x in al))
                for a, ln, c, w, s, al in data.get("attr_accesses", [])
            ),
            global_accesses=tuple(
                (str(a), int(ln), int(c), bool(w),
                 tuple(str(x) for x in s))
                for a, ln, c, w, s in data.get("global_accesses", [])
            ),
            asyncio_defs=tuple(
                str(x) for x in data.get("asyncio_defs", [])
            ),
            asyncio_touches=tuple(
                (str(a), int(b), int(c), str(d))
                for a, b, c, d in data.get("asyncio_touches", [])
            ),
            entry_regs=tuple(
                (str(a), str(b), int(c))
                for a, b, c in data.get("entry_regs", [])
            ),
            name_refs=tuple(str(x) for x in data.get("name_refs", [])),
        )


_SUSPENDERS = (ast.Await, ast.AsyncFor, ast.AsyncWith)


def compute_local_facts(mod: ModuleInfo) -> Dict[str, LocalFacts]:
    """``{qualname: LocalFacts}`` for every function in the module."""
    envs = _alias_envs(mod.tree)
    out: Dict[str, LocalFacts] = {}
    for fn_info in mod.functions.values():
        out[fn_info.qualname] = _local_facts_for(
            fn_info, envs.get(id(fn_info.node), {}), mod
        )
    return out


def _local_facts_for(
    fn_info, alias_env: Dict[str, str], mod: ModuleInfo
) -> LocalFacts:
    node = fn_info.node
    is_async = isinstance(node, ast.AsyncFunctionDef)
    blocking: List[Tuple[int, int, str, str]] = []
    acquires_raw: List[Tuple[str, int]] = []
    awaits_held_raw: set = set()
    network_awaits: List[Tuple[int, int, str]] = []
    held_at_call: List[Tuple[int, int, Tuple[str, ...]]] = []
    self_reads: set = set()
    self_writes: set = set()
    has_await = False

    attr_accesses: List[
        Tuple[str, int, int, bool, Tuple[str, ...], Tuple[str, ...]]
    ] = []
    attr_seen: set = set()
    global_accesses: List[Tuple[str, int, int, bool, Tuple[str, ...]]] = []
    global_seen: set = set()
    asyncio_defs: set = set()
    asyncio_touches: List[Tuple[str, int, int, str]] = []
    entry_regs: List[Tuple[str, str, int]] = []
    name_refs: set = set()

    store_locals, all_locals, global_decls = _scope_names(node)
    mod_globals = mod.global_names

    def is_lock_name(name: Optional[str]) -> bool:
        if name is None:
            return False
        leaf = name.rsplit(".", 1)[-1].lower()
        return leaf.endswith("lock") or leaf.endswith("mutex")

    def norm_dotted(expr: ast.AST) -> Optional[str]:
        """Dotted name with self-aliases rewritten back through self."""
        d = au.dotted_name(expr)
        if d is None:
            return None
        root, _, rest = d.partition(".")
        if root in alias_env:
            base = f"self.{alias_env[root]}"
            return f"{base}.{rest}" if rest else base
        return d

    def access_attr_of(n: ast.AST) -> Optional[str]:
        """Full dotted instance path of an access chain, through
        aliases: ``self.A.b`` -> ``A.b``; ``r.acc`` with
        ``r = self._round`` -> ``_round.acc``; subscripts are
        transparent (``r.tbl[k]`` writes into the object at
        ``_round.tbl``).  Leaf-path granularity lets a fold-lane write
        to ``_round.acc`` coexist with loop-side bookkeeping on
        ``_round.contributors`` — disjoint leaves never race."""
        parts: List[str] = []
        while isinstance(n, (ast.Attribute, ast.Subscript)):
            if isinstance(n, ast.Attribute):
                parts.append(n.attr)
            n = n.value
        if not isinstance(n, ast.Name):
            return None
        if n.id in ("self", "cls"):
            pass
        elif n.id in alias_env:
            parts.append(alias_env[n.id])
        else:
            return None
        if not parts:
            return None
        return ".".join(reversed(parts))

    def global_root_of(n: ast.AST) -> Optional[str]:
        """Module-global root name of an access chain, or None."""
        while isinstance(n, (ast.Attribute, ast.Subscript)):
            n = n.value
        if (
            isinstance(n, ast.Name)
            and n.id in mod_globals
            and n.id not in all_locals
        ):
            return n.id
        return None

    def record_attr(
        attr: str, n: ast.AST, is_write: bool,
        sheld: Tuple[str, ...], aheld: Tuple[str, ...],
    ) -> None:
        key = (attr, is_write, sheld, aheld)
        if key in attr_seen:
            return
        attr_seen.add(key)
        attr_accesses.append(
            (attr, n.lineno, n.col_offset, is_write, sheld, aheld)
        )

    def record_global(
        name: str, n: ast.AST, is_write: bool, sheld: Tuple[str, ...]
    ) -> None:
        key = (name, is_write, sheld)
        if key in global_seen:
            return
        global_seen.add(key)
        global_accesses.append(
            (name, n.lineno, n.col_offset, is_write, sheld)
        )

    def record_entry(kind: str, expr: ast.AST, line: int) -> None:
        ref = _callable_ref(expr)
        if ref is not None:
            entry_regs.append((kind, ref, line))

    def scan_call(
        n: ast.Call, sheld: Tuple[str, ...], aheld: Tuple[str, ...]
    ) -> None:
        """Entry-point registrations + asyncio touches at one call."""
        func = n.func
        leaf = None
        if isinstance(func, ast.Attribute):
            leaf = func.attr
        elif isinstance(func, ast.Name):
            leaf = func.id
        cn = au.call_name(n)
        cleaf = cn.rsplit(".", 1)[-1] if cn else leaf
        if leaf in _ROUTE_REGISTRARS:
            idx = _ROUTE_REGISTRARS[leaf]
            if len(n.args) > idx:
                record_entry("route", n.args[idx], n.lineno)
        if leaf in _LOOP_CB_REGISTRARS:
            idx = _LOOP_CB_REGISTRARS[leaf]
            if len(n.args) > idx:
                record_entry("loop_cb", n.args[idx], n.lineno)
        if cleaf == "PeriodicTask" and n.args:
            record_entry("loop_cb", n.args[0], n.lineno)
        if leaf in _THREAD_REGISTRARS:
            idx = _THREAD_REGISTRARS[leaf]
            if len(n.args) > idx:
                record_entry("thread", n.args[idx], n.lineno)
        if cleaf == "Thread":
            for kw in n.keywords:
                if kw.arg == "target":
                    record_entry("thread", kw.value, n.lineno)
        if isinstance(func, ast.Attribute):
            if func.attr in _ASYNCIO_TOUCH_METHODS:
                recv = func.value
                attr = None
                if (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id in ("self", "cls")
                ):
                    attr = recv.attr
                elif isinstance(recv, ast.Name) and recv.id in alias_env:
                    attr = alias_env[recv.id]
                if attr is not None:
                    asyncio_touches.append(
                        (attr, n.lineno, n.col_offset, func.attr)
                    )
            if func.attr in _LOOP_AFFINE_METHODS:
                asyncio_touches.append(
                    ("<loop>", n.lineno, n.col_offset, func.attr)
                )

    def scan_asyncio_def(n: ast.Assign) -> None:
        if not isinstance(n.value, ast.Call):
            return
        cn = au.call_name(n.value)
        if cn is None:
            return
        root, _, fleaf = cn.rpartition(".")
        is_factory = fleaf == "create_future" or (
            fleaf in _ASYNCIO_FACTORIES
            and (
                root == "asyncio"
                or (not root and mod.imports.get(fleaf, "").startswith(
                    "asyncio."
                ))
            )
        )
        if not is_factory:
            return
        for t in n.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id in ("self", "cls")
            ):
                asyncio_defs.add(t.attr)

    def visit(
        n: ast.AST, aheld: Tuple[str, ...], sheld: Tuple[str, ...]
    ) -> None:
        nonlocal has_await
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return  # separate execution context (to_thread closures)
        if isinstance(n, _SUSPENDERS):
            has_await = True
            awaits_held_raw.update(aheld)
        if isinstance(n, (ast.With, ast.AsyncWith)):
            is_sync_with = isinstance(n, ast.With)
            new_aheld, new_sheld = aheld, sheld
            header = [i.context_expr for i in n.items]
            for item in n.items:
                expr = item.context_expr
                raw = norm_dotted(expr)
                if is_lock_name(raw):
                    if is_sync_with:
                        new_sheld = new_sheld + (raw,)
                        visit(expr, aheld, sheld)
                    else:
                        acquires_raw.append((raw, n.lineno))
                        new_aheld = new_aheld + (raw,)
                        attr = access_attr_of(expr)
                        if attr is not None:
                            record_attr(attr, expr, False, sheld, aheld)
                elif isinstance(expr, ast.Call):
                    if is_sync_with:
                        reason = blocked_reason(expr)
                        if reason is not None:
                            blocking.append(
                                (expr.lineno, expr.col_offset,
                                 reason[0], reason[1])
                            )
                    elif is_network_call(expr):
                        network_awaits.append(
                            (expr.lineno, expr.col_offset,
                             au.call_name(expr)
                             or f"<expr>.{expr.func.attr}")
                        )
                    held_at_call.append(
                        (expr.lineno, expr.col_offset, aheld)
                    )
                    scan_call(expr, sheld, aheld)
                    for child in ast.iter_child_nodes(expr):
                        visit(child, aheld, sheld)
                else:
                    visit(expr, aheld, sheld)
            for child in ast.iter_child_nodes(n):
                if child in header or isinstance(child, ast.withitem):
                    continue
                visit(child, new_aheld, new_sheld)
            return
        if isinstance(n, ast.Await) and isinstance(n.value, ast.Call):
            if is_network_call(n.value):
                network_awaits.append(
                    (n.value.lineno, n.value.col_offset,
                     au.call_name(n.value)
                     or f"<expr>.{n.value.func.attr}")
                )
        if isinstance(n, ast.Call):
            reason = blocked_reason(n)
            if reason is not None:
                blocking.append(
                    (n.lineno, n.col_offset, reason[0], reason[1])
                )
            held_at_call.append((n.lineno, n.col_offset, aheld))
            scan_call(n, sheld, aheld)
        if isinstance(n, ast.Attribute):
            attr = (
                n.attr
                if isinstance(n.value, ast.Name)
                and n.value.id in ("self", "cls")
                else None
            )
            is_store = isinstance(n.ctx, (ast.Store, ast.Del))
            if attr is not None:
                if is_store:
                    self_writes.add(attr)
                else:
                    self_reads.add(attr)
                record_attr(attr, n, is_store, sheld, aheld)
            elif (
                isinstance(n.value, ast.Name)
                and n.value.id in alias_env
            ):
                path = access_attr_of(n)
                if path is not None:
                    record_attr(path, n, is_store, sheld, aheld)
            if (
                isinstance(n.ctx, ast.Load)
                and isinstance(n.value, ast.Name)
                and id(n) not in callfunc_ids
            ):
                base = n.value.id
                if base in ("self", "cls"):
                    name_refs.add(f"self.{n.attr}")
                elif base not in store_locals:
                    name_refs.add(f"{base}.{n.attr}")
        if isinstance(n, ast.Name):
            if (
                isinstance(n.ctx, ast.Load)
                and n.id in mod_globals
                and n.id not in all_locals
            ):
                record_global(n.id, n, False, sheld)
            elif isinstance(n.ctx, ast.Store) and n.id in global_decls:
                record_global(n.id, n, True, sheld)
            if (
                isinstance(n.ctx, ast.Load)
                and id(n) not in callfunc_ids
                and n.id not in store_locals
            ):
                name_refs.add(n.id)
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(n, ast.Assign):
                scan_asyncio_def(n)
            targets = list(
                n.targets if isinstance(n, ast.Assign) else [n.target]
            )
            # unpack `a, self.x = ...` so the attribute store is seen
            for t in list(targets):
                if isinstance(t, (ast.Tuple, ast.List)):
                    targets.extend(t.elts)
            for t in targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)):
                    attr = _self_attr_of(t)
                    if attr is not None:
                        self_writes.add(attr)
                    aattr = access_attr_of(t)
                    if aattr is not None:
                        record_attr(aattr, t, True, sheld, aheld)
                    g = global_root_of(t)
                    if g is not None:
                        record_global(g, t, True, sheld)
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _SELF_MUTATORS
        ):
            attr = _self_attr_of(n.func.value)
            if attr is not None:
                self_writes.add(attr)
            aattr = access_attr_of(n.func.value)
            if aattr is not None:
                record_attr(aattr, n, True, sheld, aheld)
            g = global_root_of(n.func.value)
            if g is not None:
                record_global(g, n, True, sheld)
        for child in ast.iter_child_nodes(n):
            visit(child, aheld, sheld)

    # call-position func Name/Attribute nodes: not "references by value"
    callfunc_ids: set = set()
    for sub in au.walk_shallow(node):
        if isinstance(sub, ast.Call) and isinstance(
            sub.func, (ast.Name, ast.Attribute)
        ):
            callfunc_ids.add(id(sub.func))

    for stmt in node.body:
        visit(stmt, (), ())

    taint_ops, returns_taint = _local_taint_facts(node)
    return LocalFacts(
        qual=fn_info.qualname,
        class_name=fn_info.class_name,
        is_async=is_async,
        has_await=has_await,
        blocking=tuple(blocking),
        acquires_raw=tuple(acquires_raw),
        awaits_held_raw=tuple(sorted(awaits_held_raw)),
        network_awaits=tuple(network_awaits),
        held_at_call=tuple(held_at_call),
        self_reads=tuple(sorted(self_reads)),
        self_writes=tuple(sorted(self_writes)),
        taint_ops=taint_ops,
        returns_param_taint=returns_taint,
        attr_accesses=tuple(attr_accesses),
        global_accesses=tuple(global_accesses),
        asyncio_defs=tuple(sorted(asyncio_defs)),
        asyncio_touches=tuple(asyncio_touches),
        entry_regs=tuple(entry_regs),
        name_refs=tuple(sorted(name_refs)),
    )


def _local_taint_facts(node) -> Tuple[tuple, bool]:
    """Host-side ops in this function that become hazards under a JAX
    trace, plus whether the return value derives from the parameters.

    ``needs_taint`` ops (casts, np materializers, ``.item()``) fire
    only when the function is CALLED with traced arguments; ``print``
    is a hazard in any traced execution (it runs at trace time only)."""
    tainted = au.param_names(node) - {"self", "cls"}
    body = node.body if isinstance(node.body, list) else [node.body]
    oracle = au.make_taint_oracle(tainted)
    for _ in range(10):
        if not au.propagate_taint(body, tainted, oracle):
            break

    ops: List[Tuple[bool, str, int, int, str]] = []
    returns_taint = False
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Return) and n.value is not None:
                if oracle(n.value):
                    returns_taint = True
            if not isinstance(n, ast.Call):
                continue
            name = au.call_name(n)
            if name == "print":
                ops.append(
                    (False, "print", n.lineno, n.col_offset,
                     "print() runs at trace time only; use "
                     "jax.debug.print for per-call output")
                )
            elif (
                name in ("float", "int", "bool", "complex")
                and n.args
                and oracle(n.args[0])
            ):
                ops.append(
                    (True, "cast", n.lineno, n.col_offset,
                     f"{name}() on a value derived from the caller's "
                     f"traced arguments concretizes the tracer")
                )
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in ("asarray", "array", "copy")
                and au.dotted_name(n.func.value) in ("np", "numpy")
                and n.args
                and oracle(n.args[0])
            ):
                ops.append(
                    (True, "materialize", n.lineno, n.col_offset,
                     f"np.{n.func.attr}() on a value derived from the "
                     f"caller's traced arguments materializes the "
                     f"tracer on host; use jnp.{n.func.attr}")
                )
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "item"
                and not n.args and not n.keywords
                and oracle(n.func.value)
            ):
                ops.append(
                    (True, "item", n.lineno, n.col_offset,
                     ".item() on a value derived from the caller's "
                     "traced arguments blocks on a device->host "
                     "transfer per trace")
                )
    return tuple(ops), returns_taint


# -- execution contexts ------------------------------------------------
@dataclasses.dataclass
class CtxWitness:
    """Why a function runs in a given execution context: the entry
    point that roots it plus the shortest call chain found from there.
    ``seed`` is the entry-point flavor ("async" | "route" | "loop_cb" |
    "thread"); ``server`` is whether the REGISTERING module is part of
    the server/obs runtime (scopes BTL005/BTL006 reporting)."""

    kind: str                  # "loop" | "thread"
    root_key: str              # function key of the entry point
    root_qual: str
    reason: str                # human wording for the entry point
    seed: str
    chain: Tuple[str, ...]     # qualnames from root (exclusive) to fn
    reg_path: str              # module registering the entry point
    reg_line: int
    server: bool


# -- fixpoint summaries ------------------------------------------------
@dataclasses.dataclass
class FnSummary:
    """What one function may do, including everything reachable through
    its resolved calls.  Site dicts map ``(path, line, col)`` to a
    payload whose last element is the witness chain (qualnames below
    this function, shortest first discovered)."""

    key: str
    qualname: str
    is_async: bool
    has_await: bool                     # this frame itself suspends
    may_suspend: bool                   # suspends here or in a callee
    # (path, line, col) -> (display, reason, chain)
    blocking: Dict[tuple, tuple] = dataclasses.field(default_factory=dict)
    # (path, line, col) -> (display, chain)
    network_awaits: Dict[tuple, tuple] = dataclasses.field(
        default_factory=dict
    )
    acquires: FrozenSet[str] = frozenset()
    awaits_held: FrozenSet[str] = frozenset()
    self_reads: FrozenSet[str] = frozenset()
    self_writes: FrozenSet[str] = frozenset()
    # (path, line, col) -> (needs_taint, kind, message, chain)
    taint_ops: Dict[tuple, tuple] = dataclasses.field(default_factory=dict)
    returns_param_taint: bool = False


def _tarjan_sccs(
    keys: Sequence[str], succ: Dict[str, List[str]]
) -> List[List[str]]:
    """Iterative Tarjan: SCCs in reverse topological order (every
    successor SCC appears before its callers)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: set = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in keys:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            children = succ.get(node, [])
            for i in range(pi, len(children)):
                child = children[i]
                if child not in index:
                    work[-1] = (node, i + 1)
                    work.append((child, 0))
                    recursed = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if recursed:
                continue
            work.pop()
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


class Summaries:
    """Fixpoint summaries for every function of a project.

    ``cached_locals`` maps module path -> ``{qual: LocalFacts}`` for
    files whose content hash matched the incremental cache; those
    modules skip the local extraction walk entirely (the fixpoint
    always reruns — it is global and cheap next to parsing)."""

    def __init__(
        self,
        project: Project,
        graph: Optional[CallGraph] = None,
        cached_locals: Optional[Dict[str, Dict[str, LocalFacts]]] = None,
    ) -> None:
        self.project = project
        self.graph = graph if graph is not None else CallGraph(project)
        self.locals: Dict[str, LocalFacts] = {}
        self.local_facts_by_path: Dict[str, Dict[str, LocalFacts]] = {}
        self.cache_hits: List[str] = []
        self.cache_misses: List[str] = []
        cached_locals = cached_locals or {}
        for mod in project.modules:
            cached = cached_locals.get(mod.path)
            if cached is not None and set(cached) == set(
                fi.qualname for fi in mod.functions.values()
            ):
                facts = cached
                self.cache_hits.append(mod.path)
            else:
                facts = compute_local_facts(mod)
                self.cache_misses.append(mod.path)
            self.local_facts_by_path[mod.path] = facts
            for fi in mod.functions.values():
                lf = facts.get(fi.qualname)
                if lf is not None:
                    self.locals[fi.key] = lf
        self.by_key: Dict[str, FnSummary] = {}
        self._compute()
        # key -> {"loop": CtxWitness, "thread": CtxWitness}
        self.contexts: Dict[str, Dict[str, CtxWitness]] = {}
        self._compute_contexts()

    def get(self, key: str) -> Optional[FnSummary]:
        return self.by_key.get(key)

    def for_function(self, fn_info) -> Optional[FnSummary]:
        return self.by_key.get(fn_info.key)

    def context_kinds(self, key: str) -> FrozenSet[str]:
        """``{"loop"}``, ``{"thread"}``, both, or empty (unrooted)."""
        return frozenset(self.contexts.get(key, ()))

    def witness(self, key: str, kind: str) -> Optional[CtxWitness]:
        return self.contexts.get(key, {}).get(kind)

    # ------------------------------------------------------------------
    def _compute(self) -> None:
        project = self.project
        graph = self.graph

        # seed every function from its local facts
        for fn in project.functions():
            lf = self.locals.get(fn.key)
            if lf is None:
                continue
            mod = fn.module
            acquires = frozenset(
                x for x in (
                    lock_identity(raw, fn.class_name, mod, project)
                    for raw, _line in lf.acquires_raw
                ) if x is not None
            )
            awaits_held = frozenset(
                x for x in (
                    lock_identity(raw, fn.class_name, mod, project)
                    for raw in lf.awaits_held_raw
                ) if x is not None
            )
            summ = FnSummary(
                key=fn.key,
                qualname=fn.qualname,
                is_async=lf.is_async,
                has_await=lf.has_await,
                may_suspend=lf.has_await,
                acquires=acquires,
                awaits_held=awaits_held,
                self_reads=frozenset(lf.self_reads),
                self_writes=frozenset(lf.self_writes),
                returns_param_taint=lf.returns_param_taint,
            )
            for line, col, display, reason in lf.blocking:
                summ.blocking[(mod.path, line, col)] = (display, reason, ())
            for line, col, display in lf.network_awaits:
                summ.network_awaits[(mod.path, line, col)] = (display, ())
            for needs, kind, line, col, msg in lf.taint_ops:
                summ.taint_ops[(mod.path, line, col)] = (
                    needs, kind, msg, ()
                )
            self.by_key[fn.key] = summ

        # held locks at each call site, normalized, for awaits_held
        held_at: Dict[str, Dict[tuple, FrozenSet[str]]] = {}
        for fn in project.functions():
            lf = self.locals.get(fn.key)
            if lf is None:
                continue
            per_site: Dict[tuple, FrozenSet[str]] = {}
            for line, col, raw_locks in lf.held_at_call:
                per_site[(line, col)] = frozenset(
                    x for x in (
                        lock_identity(r, fn.class_name, fn.module, project)
                        for r in raw_locks
                    ) if x is not None
                )
            held_at[fn.key] = per_site

        succ: Dict[str, List[str]] = {
            key: sorted({e.callee.key for e in edges})
            for key, edges in graph.edges.items()
        }
        sccs = _tarjan_sccs(sorted(self.by_key), succ)

        for scc in sccs:
            # members of one SCC iterate together until stable; a
            # singleton without a self-loop stabilizes in one pass
            for _ in range(len(scc) * 2 + 1):
                changed = False
                for key in scc:
                    if self._merge_callees(key, held_at):
                        changed = True
                if not changed:
                    break

    def _merge_callees(
        self, key: str, held_at: Dict[str, Dict[tuple, FrozenSet[str]]]
    ) -> bool:
        summ = self.by_key.get(key)
        if summ is None:
            return False
        changed = False
        for edge in self.graph.callees(key):
            callee = self.by_key.get(edge.callee.key)
            if callee is None:
                continue
            # a sync frame calling an async def only builds a coroutine
            # object — nothing in the callee executes at this site
            if callee.is_async and not summ.is_async:
                continue
            chain_step = (edge.callee.qualname,)
            for site, (display, reason, chain) in callee.blocking.items():
                if site not in summ.blocking:
                    summ.blocking[site] = (
                        display, reason, chain_step + chain
                    )
                    changed = True
            for site, (display, chain) in callee.network_awaits.items():
                if site not in summ.network_awaits:
                    summ.network_awaits[site] = (
                        display, chain_step + chain
                    )
                    changed = True
            if not callee.acquires <= summ.acquires:
                summ.acquires = summ.acquires | callee.acquires
                changed = True
            new_awaits_held = callee.awaits_held
            if callee.may_suspend:
                site_held = held_at.get(key, {}).get(
                    (edge.node.lineno, edge.node.col_offset)
                )
                if site_held:
                    new_awaits_held = new_awaits_held | site_held
                if not summ.may_suspend:
                    summ.may_suspend = True
                    changed = True
            if not new_awaits_held <= summ.awaits_held:
                summ.awaits_held = summ.awaits_held | new_awaits_held
                changed = True
            if edge.via_self:
                if not callee.self_reads <= summ.self_reads:
                    summ.self_reads = summ.self_reads | callee.self_reads
                    changed = True
                if not callee.self_writes <= summ.self_writes:
                    summ.self_writes = (
                        summ.self_writes | callee.self_writes
                    )
                    changed = True
            for site, (needs, kind, msg, chain) in callee.taint_ops.items():
                if site not in summ.taint_ops:
                    summ.taint_ops[site] = (
                        needs, kind, msg, chain_step + chain
                    )
                    changed = True
        return changed

    # ------------------------------------------------------------------
    def _compute_contexts(self) -> None:
        """Root the call graph at real runtime entry points and
        propagate a {loop, thread} context lattice along execution
        edges.  Seeds: every ``async def`` runs on the loop; a callable
        registered as a route handler / loop callback / ``PeriodicTask``
        runs on the loop; one handed to ``to_thread`` / an executor /
        ``threading.Thread`` runs on a worker thread.  Propagation into
        an ``async def`` is skipped (sync frames merely build the
        coroutine; async frames carry their own loop seed), so a
        thread-context caller never taints a coroutine it schedules."""
        project = self.project
        from collections import deque

        def fn_is_async(fn) -> bool:
            lf = self.locals.get(fn.key)
            if lf is not None:
                return lf.is_async
            return isinstance(fn.node, ast.AsyncFunctionDef)

        seeds: List[Tuple[str, CtxWitness]] = []
        for fn in project.functions():
            lf = self.locals.get(fn.key)
            if lf is None:
                continue
            server = any(p in ("server", "obs") for p in fn.module.parts)
            if lf.is_async:
                seeds.append((fn.key, CtxWitness(
                    "loop", fn.key, fn.qualname, "async def", "async",
                    (), fn.module.path, fn.node.lineno, server,
                )))
            for kind, ref, line in lf.entry_regs:
                for target in project.resolve_ref(
                    fn.module, fn.class_name, ref
                ):
                    if fn_is_async(target):
                        # a coroutine function keeps its loop seed no
                        # matter who schedules or threads it
                        continue
                    if kind == "thread":
                        w = CtxWitness(
                            "thread", target.key, target.qualname,
                            f"dispatched to a worker thread by "
                            f"{fn.qualname}()", "thread", (),
                            fn.module.path, line, server,
                        )
                    else:
                        seed = "route" if kind == "route" else "loop_cb"
                        what = (
                            "registered as a route handler"
                            if kind == "route"
                            else "scheduled as a loop callback"
                        )
                        w = CtxWitness(
                            "loop", target.key, target.qualname,
                            f"{what} by {fn.qualname}()", seed, (),
                            fn.module.path, line, server,
                        )
                    seeds.append((target.key, w))

        contexts = self.contexts
        queue: "deque[Tuple[str, str]]" = deque()

        def install(key: str, w: CtxWitness) -> None:
            cur = contexts.setdefault(key, {})
            prev = cur.get(w.kind)
            if prev is None or (w.server and not prev.server):
                cur[w.kind] = w
                queue.append((key, w.kind))

        for key, w in seeds:
            install(key, w)

        while queue:
            key, kind = queue.popleft()
            w = contexts[key][kind]
            caller = self.by_key.get(key)
            for edge in self.graph.callees(key):
                callee = self.by_key.get(edge.callee.key)
                if callee is None or callee.is_async:
                    # sync->async builds a coroutine object; async
                    # callees are loop-seeded directly
                    continue
                if caller is None:
                    continue
                install(edge.callee.key, CtxWitness(
                    kind, w.root_key, w.root_qual, w.reason, w.seed,
                    w.chain + (edge.callee.qualname,),
                    w.reg_path, w.reg_line, w.server,
                ))


def get_summaries(project: Project) -> Summaries:
    """Per-run memoized summaries: checkers share one fixpoint pass."""
    summ = getattr(project, "_summaries", None)
    if summ is None:
        cached = getattr(project, "_cached_local_facts", None)
        summ = Summaries(project, cached_locals=cached)
        project._summaries = summ
    return summ
