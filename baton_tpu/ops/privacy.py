"""Differential privacy — DP-SGD and client-level DP aggregation.

The reference has no privacy machinery at all (clients ship raw
state_dicts, reference worker.py:108-124); BASELINE config 5 (ViT-B/16
cross-silo with DP-SGD + secure aggregation) is a driver-set workload.
Two granularities, composable:

* **Example-level DP-SGD** inside local training: per-example gradients
  are one ``vmap`` over the framework's per-example loss contract
  (core/model.py — the contract exists partly *for* this), clipped to
  ``clip_norm`` each, summed, Gaussian-noised at ``noise_multiplier *
  clip_norm``, and averaged over the **static** batch size (padding rows
  have exactly-zero gradients, so they are clipped no-ops and the lot
  size stays data-independent, as the DP analysis requires). Enabled by
  passing :class:`DPConfig` to the trainer/engine.
* **Client-level DP** at aggregation: each client's round delta is
  clipped in global L2 norm, deltas are **uniformly** averaged (weighting
  by private sample counts would leak them into sensitivity), and
  Gaussian noise of std ``noise_multiplier * clip_norm / n_clients`` is
  added to the mean — the DP-FedAvg recipe.

Accounting is Rényi-DP. Without sampling each step/round is
``(α, α/(2σ²))``-RDP (:func:`rdp_epsilon`); with Poisson subsampling
(:func:`poisson_sample` drives cohort selection,
``FedSim.run_round(client_indices=…)`` consumes it) the sampled
Gaussian mechanism's amplified RDP is computed at integer orders via
the exact binomial expansion (:func:`sampled_gaussian_rdp`), composed
additively over steps, and converted with the tight RDP→(ε, δ) bound
(:func:`subsampled_rdp_epsilon`).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = Any


@dataclasses.dataclass(frozen=True)
class DPConfig:
    """Hashable DP-SGD settings (rides inside jit-static trainer fields).

    ``noise_multiplier`` is σ in the DP literature: noise std per step is
    ``noise_multiplier * clip_norm`` on the *summed* clipped gradients.

    **Scope of the guarantee**: the RDP accounting covers the *gradients*
    (and therefore the released model parameters). Reported training
    losses (``loss_history`` / ``RoundResult.client_losses``) are exact
    functions of the private data and are NOT privatized — treat them as
    diagnostics for trusted eyes only, or suppress them at the release
    boundary (``FedSim.run_round(collect_client_losses=False)``).
    """

    clip_norm: float
    noise_multiplier: float


def global_norm(tree: Params) -> jax.Array:
    """L2 norm over every leaf of a pytree, fp32."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def clip_by_global_norm(tree: Params, max_norm) -> Params:
    """Scale ``tree`` so its global L2 norm is at most ``max_norm``."""
    norm = global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(
        lambda l: (l.astype(jnp.float32) * factor).astype(l.dtype), tree
    )


def per_example_clipped_grad_sum(loss_fn, params: Params, batch, rng,
                                 clip_norm):
    """Returns (Σ_i clip(∇ loss_fn(params, example_i), clip_norm),
    per-example losses [B]).

    ``loss_fn(params, single_example_batch, rng) -> scalar`` where the
    batch dict has leading dim 1. Per-example gradients are a vmap over
    the batch axis; each is clipped to ``clip_norm`` in global L2 before
    summation — the DP-SGD sensitivity bound. Losses fall out of the
    same value_and_grad pass (no extra forward) and are NOT part of the
    DP guarantee (see :class:`DPConfig`).
    """

    def single(p, example):
        batch1 = jax.tree_util.tree_map(lambda a: a[None], example)
        return loss_fn(p, batch1, rng)

    losses, grads = jax.vmap(
        jax.value_and_grad(single), in_axes=(None, 0)
    )(params, batch)
    # per-example global norms: reduce every leaf over all but axis 0
    sq = [
        jnp.sum(jnp.square(g.astype(jnp.float32)),
                axis=tuple(range(1, g.ndim)))
        for g in jax.tree_util.tree_leaves(grads)
    ]
    norms = jnp.sqrt(sum(sq))  # [B]
    factors = jnp.minimum(1.0, clip_norm / jnp.maximum(norms, 1e-12))

    def clip_and_sum(g):
        f = factors.reshape((-1,) + (1,) * (g.ndim - 1))
        return jnp.sum(g.astype(jnp.float32) * f, axis=0)

    return jax.tree_util.tree_map(clip_and_sum, grads), losses


def gaussian_noise_like(tree: Params, std, rng) -> Params:
    """Independent N(0, std²) per element, one subkey per leaf."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        jax.random.normal(k, l.shape, jnp.float32) * std
        for k, l in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def dp_sgd_grads(loss_fn, params: Params, batch, rng, dp: DPConfig,
                 batch_size: int):
    """The DP-SGD gradient estimator: clipped per-example sum + noise,
    averaged over the static lot size.

    Returns ``(grads, per_example_losses)``; gradient leaves keep the
    parameter dtypes (lax.scan carries must be dtype-stable)."""
    grad_rng, noise_rng = jax.random.split(rng)
    summed, losses = per_example_clipped_grad_sum(
        loss_fn, params, batch, grad_rng, dp.clip_norm
    )
    noise = gaussian_noise_like(
        summed, dp.noise_multiplier * dp.clip_norm, noise_rng
    )
    grads = jax.tree_util.tree_map(
        lambda g, n, p: ((g + n) / batch_size).astype(p.dtype),
        summed, noise, params,
    )
    return grads, losses


# ---------------------------------------------------------------------------
# client-level DP aggregation (DP-FedAvg)


def dp_client_deltas(stacked_params: Params, global_params: Params,
                     clip_norm) -> Params:
    """Per-client round deltas clipped to ``clip_norm`` in global L2.

    ``stacked_params`` has a leading client axis on every leaf.
    """

    def delta(stacked_leaf, global_leaf):
        return stacked_leaf.astype(jnp.float32) - global_leaf.astype(jnp.float32)

    deltas = jax.tree_util.tree_map(delta, stacked_params, global_params)
    sq = [
        jnp.sum(jnp.square(l), axis=tuple(range(1, l.ndim)))
        for l in jax.tree_util.tree_leaves(deltas)
    ]
    norms = jnp.sqrt(sum(sq))  # [C]
    factors = jnp.minimum(1.0, clip_norm / jnp.maximum(norms, 1e-12))

    def clip(l):
        return l * factors.reshape((-1,) + (1,) * (l.ndim - 1))

    return jax.tree_util.tree_map(clip, deltas)


def dp_fedavg(stacked_params: Params, global_params: Params, rng,
              clip_norm, noise_multiplier) -> Params:
    """DP-FedAvg: uniform mean of clipped client deltas + Gaussian noise.

    Replaces sample-weighted FedAvg (reference manager.py:119-126
    semantics) when client-level DP is on: weighting by private
    ``n_samples`` would make sensitivity data-dependent, so the mean is
    uniform and the noise std is ``noise_multiplier * clip_norm / C``.
    Returns new global params (same dtypes as ``global_params``).
    """
    deltas = dp_client_deltas(stacked_params, global_params, clip_norm)
    n_clients = jax.tree_util.tree_leaves(deltas)[0].shape[0]
    mean_delta = jax.tree_util.tree_map(
        lambda l: jnp.mean(l, axis=0), deltas
    )
    noise = gaussian_noise_like(
        mean_delta, noise_multiplier * clip_norm / n_clients, rng
    )
    return jax.tree_util.tree_map(
        lambda g, d, n: (g.astype(jnp.float32) + d + n).astype(g.dtype),
        global_params, mean_delta, noise,
    )


# ---------------------------------------------------------------------------
# Rényi-DP accounting (Gaussian mechanism, exact composition)

DEFAULT_ORDERS = tuple([1.25, 1.5, 2.0, 2.5, 3.0, 4.0, 5.0, 6.0, 8.0, 10.0,
                        16.0, 32.0, 64.0, 128.0, 256.0])


def rdp_epsilon(noise_multiplier: float, steps: int, delta: float,
                orders: Sequence[float] = DEFAULT_ORDERS) -> float:
    """(ε, δ)-DP spent by ``steps`` Gaussian mechanisms of parameter σ.

    Each step is (α, α/(2σ²))-RDP; RDP composes additively; the
    conversion ε = min_α [T·α/(2σ²) + log(1/δ)/(α−1)] uses the standard
    RDP→DP bound. Conservative under subsampling (no amplification
    claimed).
    """
    if noise_multiplier <= 0:
        return float("inf")
    sigma2 = noise_multiplier ** 2
    eps = [
        steps * a / (2.0 * sigma2) + np.log(1.0 / delta) / (a - 1.0)
        for a in orders
        if a > 1.0
    ]
    return float(min(eps))


# ---------------------------------------------------------------------------
# Poisson subsampling + amplified accounting (sampled Gaussian mechanism)

# Integer Rényi orders: the exact SGM expansion below holds at integer α;
# the dense low range covers high-privacy regimes, the powers of two reach
# the tiny-q regimes where the optimum α is large.
INT_ORDERS = tuple(list(range(2, 33)) + [40, 48, 64, 96, 128, 192, 256, 512])


def poisson_sample(rng: np.random.Generator, n: int, q: float) -> np.ndarray:
    """Poisson sampling: each of ``n`` clients/examples independently
    joins with probability ``q``. Returns the (possibly empty) sorted
    index array — feed it to ``FedSim.run_round(client_indices=…)``.

    Host-side by design: cohort selection happens at dispatch time and
    its *size varies* round to round — exactly what the amplification
    theorem requires and what a static jit shape cannot express. (The
    engine pads each wave to the device multiple, so the varying cohort
    recompiles only when it crosses a wave-size boundary.)
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate must be in [0, 1], got {q}")
    return np.flatnonzero(rng.random(n) < q)


def _log_comb(n: int, k: int) -> float:
    return (math.lgamma(n + 1) - math.lgamma(k + 1)
            - math.lgamma(n - k + 1))


def sampled_gaussian_rdp(
    q: float, noise_multiplier: float,
    orders: Sequence[int] = INT_ORDERS,
) -> np.ndarray:
    """Per-step RDP of the Poisson-sampled Gaussian mechanism.

    At integer order α the SGM satisfies (α, ε_α)-RDP with

        ε_α = log( Σ_{k=0}^{α} C(α,k) (1−q)^{α−k} q^k ·
                   exp(k(k−1)/(2σ²)) ) / (α−1)

    (Mironov et al. 2019, "Rényi DP of the Sampled Gaussian Mechanism",
    Thm. 4/§3.3 — the standard accountant's integer-order path). The sum
    is evaluated in log space; q=0 gives 0, q=1 recovers the unamplified
    α/(2σ²) exactly.
    """
    if noise_multiplier <= 0:
        return np.full(len(orders), np.inf)
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling rate must be in [0, 1], got {q}")
    sigma2 = noise_multiplier ** 2
    out = []
    for a in orders:
        if a != int(a) or a < 2:
            raise ValueError(f"integer orders >= 2 only, got {a}")
        a = int(a)
        if q == 0.0:
            out.append(0.0)
            continue
        log_terms = []
        for k in range(a + 1):
            t = k * (k - 1) / (2.0 * sigma2)
            if q < 1.0:
                t += (_log_comb(a, k) + (a - k) * math.log1p(-q)
                      + (k * math.log(q) if k else 0.0))
            elif k < a:
                continue  # q == 1: only the k == α term survives
            log_terms.append(t)
        m = max(log_terms)
        log_a = m + math.log(sum(math.exp(t - m) for t in log_terms))
        out.append(log_a / (a - 1))
    return np.asarray(out)


def rdp_to_epsilon(rdp: Sequence[float], orders: Sequence[int],
                   delta: float) -> float:
    """Tight RDP→(ε, δ) conversion, minimized over orders:

        ε = rdp_α + log((α−1)/α) − (log δ + log α)/(α−1)

    (Canonne–Kamath–Steinke 2020 refinement of the classic
    ``rdp + log(1/δ)/(α−1)`` bound — the conversion production DP-SGD
    accountants report.)
    """
    best = np.inf
    for r, a in zip(rdp, orders):
        if not np.isfinite(r):
            continue
        eps = (r + math.log1p(-1.0 / a)
               - (math.log(delta) + math.log(a)) / (a - 1))
        best = min(best, max(eps, 0.0))
    return float(best)


def subsampled_rdp_epsilon(
    noise_multiplier: float,
    steps: int,
    delta: float,
    sampling_rate: float,
    orders: Sequence[int] = INT_ORDERS,
) -> float:
    """(ε, δ) spent by ``steps`` Poisson-subsampled Gaussian mechanisms.

    The amplified counterpart of :func:`rdp_epsilon`: with sampling rate
    q = lot/population (example-level DP-SGD) or cohort/registry
    (client-level DP-FedAvg), per-step RDP shrinks roughly like q²·α/σ²
    for small q — orders of magnitude over the unamplified bound.
    Validated against the canonical MNIST DP-SGD setting (σ=1.1,
    q=256/60000, 60 epochs, δ=1e-5): the classic conversion reproduces
    the folklore ε=3.0 to three digits, the tight conversion reports
    ε≈2.60 (tests/test_privacy.py).
    """
    rdp = sampled_gaussian_rdp(sampling_rate, noise_multiplier, orders)
    return rdp_to_epsilon(rdp * steps, orders, delta)
