from baton_tpu.ops.aggregation import (
    weighted_tree_mean,
    weighted_tree_sum,
    psum_weighted_mean,
    tree_stack,
    tree_unstack,
)
from baton_tpu.ops.padding import pad_dataset, pad_to_capacity

__all__ = [
    "weighted_tree_mean",
    "weighted_tree_sum",
    "psum_weighted_mean",
    "tree_stack",
    "tree_unstack",
    "pad_dataset",
    "pad_to_capacity",
]
