from baton_tpu.ops.aggregation import (
    weighted_tree_mean,
    weighted_tree_sum,
    psum_weighted_mean,
    tree_stack,
    tree_unstack,
)
from baton_tpu.ops.padding import pad_dataset, pad_to_capacity
from baton_tpu.ops.privacy import (
    DPConfig,
    clip_by_global_norm,
    dp_fedavg,
    global_norm,
    poisson_sample,
    rdp_epsilon,
    sampled_gaussian_rdp,
    subsampled_rdp_epsilon,
)
from baton_tpu.ops.secure_agg import (
    aggregate_masked,
    mask_update,
    net_mask_of,
)

__all__ = [
    "weighted_tree_mean",
    "weighted_tree_sum",
    "psum_weighted_mean",
    "tree_stack",
    "tree_unstack",
    "pad_dataset",
    "pad_to_capacity",
    "DPConfig",
    "clip_by_global_norm",
    "dp_fedavg",
    "global_norm",
    "poisson_sample",
    "rdp_epsilon",
    "sampled_gaussian_rdp",
    "subsampled_rdp_epsilon",
    "aggregate_masked",
    "mask_update",
    "net_mask_of",
]
