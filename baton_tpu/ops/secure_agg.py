"""Secure aggregation — pairwise-masked sums in a finite field.

The reference manager sees every client's raw weights (reference
manager.py:95-126); BASELINE config 5 requires the server to learn
*only the sum*. This implements the standard pairwise-masking core
(Bonawitz et al.-style):

* Updates are **fixed-point quantized** into the ring Z_2^32
  (:func:`quantize` / :func:`dequantize`) — masking must be exact, and
  float addition is not associative; uint32 modular arithmetic is.
* For every client pair ``i < j`` a mask tree is derived from a shared
  pairwise key (``jax.random.fold_in`` chain — stands in for the
  Diffie-Hellman agreed seed of the real protocol); client ``i`` adds
  it, client ``j`` subtracts it, so the masks cancel **exactly** in the
  modular sum and any single masked update is uniform noise to the
  server.
* **Dropout recovery**: if clients drop after masking, the survivors'
  sum still contains their uncancelled pairwise masks.
  :func:`net_mask_of` recomputes any client's net mask so the server can
  subtract the residue (the real protocol gates this on secret-shared
  seed recovery; the HTTP edge owns that handshake — this is the
  primitive).

This module is **host-side by design** (numpy uint32, not jnp): it runs
at the HTTP edge where real clients ship updates to an untrusted
aggregator, exact 32-bit modular arithmetic is required (JAX defaults to
32-bit-only and would truncate the intermediate 64-bit products), and
there is nothing here for the MXU to accelerate. For simulated cohorts
prefer :mod:`baton_tpu.ops.aggregation` — the server is the same
process, so there is nothing to hide. Costs are the protocol's inherent
O(C²) pairwise masks.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np

Params = Any

DEFAULT_SCALE_BITS = 16  # fixed-point fractional bits
_RING = 1 << 32


def quantize(tree: Params, scale_bits: int = DEFAULT_SCALE_BITS) -> Params:
    """Float pytree -> uint32 fixed-point (two's complement in Z_2^32).

    Exact for magnitudes < 2^(31 - scale_bits - log2 C) summed over C
    clients; callers clip updates (ops/privacy.py) before quantizing.
    """
    scale = float(1 << scale_bits)

    def one(leaf):
        q = np.round(np.asarray(leaf, np.float64) * scale).astype(np.int64)
        return (q % _RING).astype(np.uint32)

    return jax.tree_util.tree_map(one, tree)


def dequantize(tree: Params, scale_bits: int = DEFAULT_SCALE_BITS) -> Params:
    """uint32 ring elements -> float64, values >= 2^31 read as negative."""
    scale = float(1 << scale_bits)

    def one(leaf):
        v = np.asarray(leaf, np.int64)
        v = np.where(v >= _RING // 2, v - _RING, v)
        return v.astype(np.float64) / scale

    return jax.tree_util.tree_map(one, tree)


def _pair_key(seed_key, i: int, j: int):
    """Shared key for the (unordered) pair i<j."""
    lo, hi = (i, j) if i < j else (j, i)
    return jax.random.fold_in(jax.random.fold_in(seed_key, lo), hi)


def _mask_tree(key, template: Params) -> Params:
    """Uniform uint32 ring elements shaped like ``template``."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    keys = jax.random.split(key, len(leaves))
    masks = [
        np.asarray(jax.random.bits(k, np.shape(l), "uint32"))
        for k, l in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, masks)


def net_mask_of(seed_key, client: int, n_clients: int,
                template: Params) -> Params:
    """Client's total mask: Σ_{j>c} m(c,j) − Σ_{j<c} m(j,c)  (mod 2^32)."""
    total = jax.tree_util.tree_map(
        lambda l: np.zeros(np.shape(l), np.uint32), template
    )
    for other in range(n_clients):
        if other == client:
            continue
        mask = _mask_tree(_pair_key(seed_key, client, other), template)
        if other > client:
            total = jax.tree_util.tree_map(
                lambda t, m: (t + m).astype(np.uint32), total, mask
            )
        else:
            total = jax.tree_util.tree_map(
                lambda t, m: (t - m).astype(np.uint32), total, mask
            )
    return total


def mask_update(update: Params, seed_key, client: int, n_clients: int,
                scale_bits: int = DEFAULT_SCALE_BITS) -> Params:
    """Client-side: quantize and add the net pairwise mask (mod 2^32)."""
    q = quantize(update, scale_bits)
    mask = net_mask_of(seed_key, client, n_clients, q)
    return jax.tree_util.tree_map(
        lambda a, m: (a + m).astype(np.uint32), q, mask
    )


def aggregate_masked(masked_updates: Sequence[Params],
                     scale_bits: int = DEFAULT_SCALE_BITS,
                     dropped_net_masks: Sequence[Params] = ()) -> Params:
    """Server-side: modular sum of masked updates -> dequantized float sum.

    With a full cohort the pairwise masks cancel identically. If clients
    dropped after masking, pass their :func:`net_mask_of` trees: the
    survivors' residual masks toward a dropped client sum to exactly the
    negation of that client's net mask, so adding it cancels the residue.
    """
    total = jax.tree_util.tree_map(
        lambda l: np.asarray(l, np.uint32), masked_updates[0]
    )
    for u in masked_updates[1:]:
        total = jax.tree_util.tree_map(
            lambda a, b: (a + np.asarray(b, np.uint32)).astype(np.uint32),
            total, u,
        )
    for m in dropped_net_masks:
        total = jax.tree_util.tree_map(
            lambda a, b: (a + np.asarray(b, np.uint32)).astype(np.uint32),
            total, m,
        )
    return dequantize(total, scale_bits)
