"""Update compression for the HTTP edge — top-k sparsification with
error feedback, and stochastic int8 quantization.

The reference ships full pickled state_dicts both directions every round
(reference manager.py:85, worker.py:117); real cross-silo federations are
upload-bound, and the standard fixes are (a) send the round *delta*
rather than the weights, sparsified to the top-k largest-magnitude
coordinates with the dropped mass carried forward ("error feedback", so
the compressor is unbiased over time), and (b) stochastic fixed-point
quantization (unbiased per draw). Both compose with sample-weighted
FedAvg because the mean of deltas is the delta of the mean:

    mean_w(anchor + d_i) = anchor + mean_w(d_i)

so the manager reconstructs ``anchor + decompress(payload)`` per upload
and aggregates as usual (server/http_manager.py).

TPU-first notes: ``k`` is static per leaf (a fraction of its size), so
``top_k`` compiles to fixed shapes and the whole compressor jits; it is
equally happy on host NumPy arrays via jnp, which is where the HTTP
worker calls it (the payload crosses the network, not the ICI — on-mesh
simulated cohorts never need this, their "network" is a psum).

Incompatible with secure aggregation by construction: masking requires
every upload to be a dense ring element (ops/secure_agg.py), and a
sparse support set would itself leak which coordinates changed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from baton_tpu.core.model import Params


def _leaf_k(size: int, frac: float) -> int:
    return max(1, min(size, int(round(size * frac))))


def topk_compress(
    tree: Params, frac: float, residual: Optional[Params] = None
) -> Tuple[Params, Params]:
    """Keep the top ``frac`` fraction of coordinates per leaf (by
    magnitude); everything else goes into the returned residual.

    Returns ``(payload, new_residual)``. ``payload`` mirrors the input
    structure with ``{"idx": int32[k], "val": f32[k], "size": int}``
    leaves (flat indexing). With ``residual`` from the previous round the
    input is pre-corrected: compress(tree + residual) — error feedback.
    """
    if not (0.0 < frac <= 1.0):
        raise ValueError(f"frac must be in (0, 1], got {frac}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    res_leaves = (
        jax.tree_util.tree_flatten(residual)[0]
        if residual is not None
        else [None] * len(leaves)
    )
    payloads, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        flat = jnp.ravel(jnp.asarray(leaf, jnp.float32))
        if res is not None:
            flat = flat + jnp.ravel(jnp.asarray(res, jnp.float32))
        k = _leaf_k(flat.size, frac)
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        val = flat[idx]
        kept = jnp.zeros_like(flat).at[idx].set(val)
        payloads.append({
            "idx": idx.astype(jnp.int32),
            "val": val,
            "size": int(flat.size),
        })
        new_res.append((flat - kept).reshape(jnp.shape(leaf)))
    return (
        jax.tree_util.tree_unflatten(treedef, payloads),
        jax.tree_util.tree_unflatten(treedef, new_res),
    )


def topk_decompress(payload: Params, template: Params) -> Params:
    """Reconstruct dense leaves shaped like ``template`` from a
    :func:`topk_compress` payload."""

    def one(p, t):
        dense = jnp.zeros((p["size"],), jnp.float32).at[
            jnp.asarray(p["idx"])
        ].set(jnp.asarray(p["val"], jnp.float32))
        return dense.reshape(jnp.shape(t))

    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    p_leaves = treedef.flatten_up_to(payload)
    return jax.tree_util.tree_unflatten(
        treedef, [one(p, t) for p, t in zip(p_leaves, t_leaves)]
    )


def quantize_stochastic(
    tree: Params, rng: jax.Array, bits: int = 8
) -> Params:
    """Unbiased fixed-point quantization: each leaf becomes
    ``{"q": int8/int16[...], "scale": f32}`` with stochastic rounding
    (E[dequantize] == input, exactly)."""
    if bits not in (8, 16):
        raise ValueError("bits must be 8 or 16")
    qmax = float(2 ** (bits - 1) - 1)
    dtype = jnp.int8 if bits == 8 else jnp.int16
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    rngs = jax.random.split(rng, len(leaves))
    out = []
    for leaf, r in zip(leaves, rngs):
        x = jnp.asarray(leaf, jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12) / qmax
        y = x / scale
        lo = jnp.floor(y)
        # P(round up) = frac(y) -> unbiased
        up = jax.random.uniform(r, x.shape) < (y - lo)
        q = jnp.clip(lo + up, -qmax, qmax).astype(dtype)
        out.append({"q": q, "scale": scale})
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize(tree: Params) -> Params:
    def one(p):
        return jnp.asarray(p["q"], jnp.float32) * p["scale"]

    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda x: isinstance(x, dict) and "q" in x
    )


@dataclasses.dataclass
class ErrorFeedbackCompressor:
    """Stateful top-k compressor for a worker's round deltas.

    Carries the residual across rounds so the *sum* of transmitted
    updates tracks the sum of true updates (EF-SGD): nothing the
    compressor drops is ever lost, only delayed.
    """

    frac: float
    bits: Optional[int] = None  # additionally quantize kept values
    residual: Optional[Params] = None
    # Seed the quantizer DIFFERENTLY per client (http_worker threads its
    # rng_seed here): identical keys would correlate every client's
    # rounding errors and the cohort mean's quantization noise would
    # stop shrinking with cohort size.
    seed: int = 0
    _rng: Optional[jax.Array] = None
    _last_exact: Optional[Params] = dataclasses.field(
        default=None, repr=False
    )

    def compress(self, delta: Params) -> Params:
        payload, self.residual = topk_compress(delta, self.frac,
                                               self.residual)
        # pre-quantization payload kept for restore(): the EF invariant
        # must hold exactly per event, not just in expectation
        self._last_exact = payload
        if self.bits is not None:
            # quantization error is NOT fed back: stochastic rounding is
            # already unbiased per draw, so only top-k's (biased)
            # truncation needs the residual
            if self._rng is None:
                self._rng = jax.random.key(self.seed)
            self._rng, sub = jax.random.split(self._rng)
            is_payload = lambda x: isinstance(x, dict) and "idx" in x
            n = len(jax.tree_util.tree_leaves(payload, is_leaf=is_payload))
            rngs = iter(jax.random.split(sub, max(n, 1)))

            def swap(p):
                q = quantize_stochastic({"v": p["val"]}, next(rngs),
                                        self.bits)
                return dict(p, val=q["v"])

            payload = jax.tree_util.tree_map(
                swap, payload, is_leaf=is_payload
            )
        return payload

    def restore(self, template: Params) -> None:
        """Fold the last ``compress()``'s kept mass back into the
        residual. Call when the upload FAILS (connection error, stale
        round, auth reset): ``compress`` already moved the kept mass out
        of the residual as "transmitted", and dropping it silently would
        lose it for good — violating the EF guarantee that dropped mass
        is only ever delayed. Restores the EXACT pre-quantization values
        (the invariant holds per event, not just in expectation)."""
        if self._last_exact is None:
            return
        dense = topk_decompress(self._last_exact, template)
        self._last_exact = None
        if self.residual is None:
            self.residual = dense
        else:
            self.residual = jax.tree_util.tree_map(
                lambda r, d: (jnp.asarray(r, jnp.float32) + d), self.residual,
                dense,
            )


def decompress_payload(payload: Params, template: Params) -> Params:
    """Decode a payload whose ``val`` entries may be quantized."""

    def undo(p):
        val = p["val"]
        if isinstance(val, dict) and "q" in val:
            val = jnp.asarray(val["q"], jnp.float32) * val["scale"]
        return dict(p, val=val)

    payload = jax.tree_util.tree_map(
        undo, payload, is_leaf=lambda x: isinstance(x, dict) and "idx" in x
    )
    return topk_decompress(payload, template)


# ---------------------------------------------------------------------------
# downlink (broadcast) quantization — the server->client half of the
# bandwidth story. The manager quantizes the round's state dict once per
# round; every cohort member dequantizes the SAME tensors, so all clients
# still start from identical params (which also keeps secure-aggregation
# and sparse-upload anchors consistent).


def quantize_state_dict(
    state: Dict[str, Any], seed: int, bits: int = 8
) -> Dict[str, Any]:
    """Flat wire layout: ``{"<name>@q": intN, "<name>@qscale": f32[1]}``.
    Stochastic rounding (unbiased) seeded per round."""
    q = quantize_stochastic(dict(state), jax.random.key(seed), bits=bits)
    out: Dict[str, Any] = {}
    for k, p in q.items():
        out[f"{k}@q"] = p["q"]
        out[f"{k}@qscale"] = jnp.asarray([p["scale"]], jnp.float32)
    return out


def dequantize_state_dict(tensors: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`quantize_state_dict` (accepts numpy or jnp)."""
    import numpy as np

    out: Dict[str, Any] = {}
    for k in tensors:
        if not k.endswith("@q"):
            continue
        name = k[: -len("@q")]
        scale = float(np.asarray(tensors[f"{name}@qscale"]).ravel()[0])
        out[name] = np.asarray(tensors[k], np.float32) * scale
    if not out:
        raise ValueError("no quantized tensors found in payload")
    return out


# ---------------------------------------------------------------------------
# broadcast delta blobs — the downlink analogue of the uplink top-k path.
# The manager encodes prev_round -> this_round as a (sparse and/or
# quantized) delta ONCE per round; any worker still anchored on the
# previous round's blob downloads the small delta instead of the full
# model. Because the delta is lossy, BOTH sides define the round's
# broadcast as the RECONSTRUCTION ``apply_delta(anchor, delta)`` — pure
# sequential numpy fp32, bit-identical on manager and worker — so the
# worker can re-encode its reconstruction and verify it hashes to the
# round blob's digest (falling back to the full download on mismatch).


def parse_delta_spec(spec: str) -> Dict[str, Any]:
    """``"q8" | "q16" | "topk:<frac>" | "topk:<frac>:q8|q16"`` ->
    ``{"frac": Optional[float], "bits": Optional[int]}``.

    Mirrors the worker-side upload compression specs so operators tune
    both directions with one vocabulary."""
    frac: Optional[float] = None
    bits: Optional[int] = None
    parts = spec.split(":")
    if parts[0] == "topk":
        if len(parts) not in (2, 3):
            raise ValueError(f"bad delta spec {spec!r}")
        frac = float(parts[1])
        if not (0.0 < frac <= 1.0):
            raise ValueError(f"delta top-k frac must be in (0, 1], got {frac}")
        if len(parts) == 3:
            parts = [parts[2]]
        else:
            parts = []
    if parts:
        if len(parts) != 1 or parts[0] not in ("q8", "q16"):
            raise ValueError(
                f"unknown delta spec {spec!r}; expected 'q8', 'q16', "
                "'topk:<frac>', or 'topk:<frac>:q8|q16'"
            )
        bits = int(parts[0][1:])
    return {"frac": frac, "bits": bits}


def delta_encode_state_dict(
    prev: Dict[str, Any],
    new: Dict[str, Any],
    spec: Dict[str, Any],
    seed: int = 0,
) -> Dict[str, Any]:
    """Encode ``new - prev`` per tensor under a :func:`parse_delta_spec`.

    Flat wire layouts (matching the repo's existing conventions):
    top-k  -> ``{"<k>@idx": int64[k], "<k>@val": f32[k] | int8/16[k],
    "<k>@scale": f32[1]}`` (``@scale`` only when quantized); dense
    quantized -> ``{"<k>@q": intN[shape], "<k>@qscale": f32[1]}``.

    Pure numpy on purpose: the encode runs once per round on the manager
    host and must not depend on XLA reduction order."""
    import numpy as np

    frac, bits = spec["frac"], spec["bits"]
    rng = np.random.default_rng(seed)
    qmax = float(2 ** ((bits or 8) - 1) - 1)
    qdtype = np.int8 if (bits or 8) == 8 else np.int16

    def stoch_round(x: "np.ndarray") -> "np.ndarray":
        lo = np.floor(x)
        up = rng.random(x.shape, dtype=np.float32) < (x - lo)
        return np.clip(lo + up, -qmax, qmax)

    out: Dict[str, Any] = {}
    for k, prev_arr in prev.items():
        p32 = np.asarray(prev_arr, np.float32).ravel()
        n32 = np.asarray(new[k], np.float32).ravel()
        d = n32 - p32
        if frac is not None:
            kk = _leaf_k(d.size, frac)
            idx = np.argpartition(np.abs(d), d.size - kk)[d.size - kk:]
            idx = np.sort(idx).astype(np.int64)
            val = d[idx]
            out[f"{k}@idx"] = idx
            if bits is not None:
                scale = max(float(np.max(np.abs(val))), 1e-12) / qmax
                out[f"{k}@val"] = stoch_round(val / np.float32(scale)).astype(qdtype)
                out[f"{k}@scale"] = np.asarray([scale], np.float32)
            else:
                out[f"{k}@val"] = val.astype(np.float32)
        elif bits is not None:
            scale = max(float(np.max(np.abs(d))), 1e-12) / qmax
            shape = np.asarray(prev_arr).shape
            out[f"{k}@q"] = (
                stoch_round(d / np.float32(scale)).astype(qdtype).reshape(shape)
            )
            out[f"{k}@qscale"] = np.asarray([scale], np.float32)
        else:
            raise ValueError("delta spec must sparsify and/or quantize")
    return out


def apply_delta_state_dict(
    anchor: Dict[str, Any], delta: Dict[str, Any]
) -> Dict[str, Any]:
    """Reconstruct the round broadcast: ``anchor + decode(delta)``.

    Deterministic sequential numpy fp32 (then cast back to each anchor
    tensor's dtype) so manager and worker reconstructions are
    bit-identical — that is what makes the worker's digest verification
    of ``wire.encode(reconstruction)`` meaningful."""
    import numpy as np

    out: Dict[str, Any] = {}
    for k, ref in anchor.items():
        ref_np = np.asarray(ref)
        ref32 = ref_np.astype(np.float32).ravel()
        if f"{k}@q" in delta:
            scale = np.float32(np.asarray(delta[f"{k}@qscale"]).ravel()[0])
            dense = np.asarray(delta[f"{k}@q"], np.float32).ravel() * scale
        elif f"{k}@idx" in delta:
            val = np.asarray(delta[f"{k}@val"], np.float32)
            if f"{k}@scale" in delta:
                val = val * np.float32(
                    np.asarray(delta[f"{k}@scale"]).ravel()[0]
                )
            dense = np.zeros(ref32.size, np.float32)
            dense[np.asarray(delta[f"{k}@idx"], np.int64)] = val
        else:
            raise KeyError(f"delta payload missing tensor {k!r}")
        out[k] = (ref32 + dense).reshape(ref_np.shape).astype(ref_np.dtype)
    return out
