"""``python -m baton_tpu.ops`` — the live fleet ops console.

Thin entry point; everything lives in :mod:`baton_tpu.ops.console` so
the poll/render helpers are importable (and testable) without argv.
"""

import sys

from baton_tpu.ops.console import main

if __name__ == "__main__":
    sys.exit(main())
