"""Fused flash attention — the transformer zoo's hot op as a Pallas
TPU kernel.

The reference has no attention at all (its demo model is a 10→1 linear
layer, reference demo.py:15-49); this kernel exists for the model
families the new framework adds (BERT/Llama/ViT — BASELINE configs 3-5),
replacing the dense ``dot_product_attention`` einsum path
(baton_tpu/models/transformer.py) on the hot path:

* **never materializes the L×L score matrix in HBM** — scores live as
  one [block_q, block_k] VMEM tile at a time, with the online softmax
  (running max/sum rescaling) recurrence, so attention memory is
  O(L·Dh) instead of O(L²);
* **MXU-shaped**: every contraction is a ``jnp.dot`` with
  ``preferred_element_type=float32`` over 128-aligned tiles; softmax
  algebra rides the VPU in fp32 regardless of input dtype;
* **trains**: a custom VJP with a Pallas backward kernel recomputes
  p = exp(s − lse) blockwise from the saved logsumexp — the standard
  flash-attention backward — so the O(L²) probs are never stored for
  the backward pass either;
* **GQA for free**: the kv-head block index map sends query head ``h``
  to kv head ``h // (Hq//Hkv)`` — no ``jnp.repeat`` materialization;
* matches the seam contract ``attention_fn(q, k, v, bias, causal)``
  (transformer.py:31-32): additive per-key bias [B, 1, 1, L], static
  causal masking from global positions.

On CPU (tests, the 8-device virtual mesh) the kernel runs in Pallas
interpret mode — same code path, bit-compatible math.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

NEG_INF = -1e30


def _spec(block, index_map):
    if _VMEM is None:
        return pl.BlockSpec(block, index_map)
    return pl.BlockSpec(block, index_map, memory_space=_VMEM)


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ======================================================================
# forward kernel: grid (B, Hq, Lq/block_q)


def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                *, scale, causal, block_q, block_k, lk):
    i = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
    nk = lk // block_k

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = i * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, carry):
        m, l, acc = carry
        kj = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kj.T, preferred_element_type=jnp.float32)  # [bq, bk]
        s = s + b_ref[0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, vj, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    group = hq // hkv
    grid = (b, hq, lq // block_q)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, lk=lk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _spec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            _spec((1, 1, lk, d), lambda b_, h, i: (b_, h // group, 0, 0)),
            _spec((1, 1, lk, d), lambda b_, h, i: (b_, h // group, 0, 0)),
            _spec((1, lk), lambda b_, h, i: (b_, 0)),
        ],
        out_specs=[
            _spec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            _spec((1, 1, block_q), lambda b_, h, i: (b_, h, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, lq), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, bias2d)
    return out, lse


# ======================================================================
# backward: the standard two-pass flash-attention backward, blockwise
# recompute of p from the saved lse (no O(L²) residuals). Pass 1 grids
# (B, Hq, Lk/block_k, Lq/block_q) and accumulates dk/dv/db over the
# innermost q axis; pass 2 grids (B, Hq, Lq/block_q, Lk/block_k) and
# accumulates dq over the innermost kv axis. Only block-sized tiles are
# ever VMEM-resident, so VMEM is O(block²), independent of L (the r1
# single-program-per-head version held ~7 full [L, d] buffers).
# delta = rowsum(do·o) is precomputed outside pallas.


def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, b_ref,
                    dk_ref, dv_ref, db_ref, *, scale, causal,
                    block_q, block_k):
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])
        db_ref[0, 0] = jnp.zeros_like(db_ref[0, 0])

    qi = q_ref[0, 0].astype(jnp.float32) * scale               # [bq, D]
    doi = do_ref[0, 0].astype(jnp.float32)                     # [bq, D]
    lsei = lse_ref[0, 0][:, None]                              # [bq, 1]
    delta = delta_ref[0, 0][:, None]                           # [bq, 1]
    kj = k_ref[0, 0].astype(jnp.float32)                       # [bk, D]
    vj = v_ref[0, 0].astype(jnp.float32)
    bj = b_ref[0][None, :]                                     # [1, bk]

    s = jnp.dot(qi, kj.T, preferred_element_type=jnp.float32) + bj
    if causal:
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lsei)                                      # [bq, bk]
    dp = jnp.dot(doi, vj.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)                                      # [bq, bk]
    dv_ref[0, 0] += jnp.dot(p.T, doi, preferred_element_type=jnp.float32)
    dk_ref[0, 0] += jnp.dot(ds.T, qi, preferred_element_type=jnp.float32)
    db_ref[0, 0] += ds.sum(axis=0)


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, b_ref,
                   dq_ref, *, scale, causal, block_q, block_k):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    qi = q_ref[0, 0].astype(jnp.float32) * scale
    doi = do_ref[0, 0].astype(jnp.float32)
    lsei = lse_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    kj = k_ref[0, 0].astype(jnp.float32)
    vj = v_ref[0, 0].astype(jnp.float32)
    bj = b_ref[0][None, :]

    s = jnp.dot(qi, kj.T, preferred_element_type=jnp.float32) + bj
    if causal:
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lsei)
    dp = jnp.dot(doi, vj.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_ref[0, 0] += scale * jnp.dot(
        ds, kj, preferred_element_type=jnp.float32
    )


def _bwd_call(q, k, v, bias2d, out, dout, lse,
              causal, scale, block_q, block_k, interpret):
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    group = hq // hkv
    nq, nk = lq // block_q, lk // block_k

    # delta [B, Hq, Lq] in fp32 — cheap elementwise reduce, let XLA fuse it
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )

    def in_specs(qi, kj):
        """Common input specs; ``qi``/``kj`` pick the q/kv block index out
        of the two trailing grid axes (x, y)."""
        q_spec = _spec((1, 1, block_q, d),
                       lambda b_, h, x, y: (b_, h, qi(x, y), 0))
        lse_spec = _spec((1, 1, block_q),
                         lambda b_, h, x, y: (b_, h, qi(x, y)))
        kv_spec = _spec((1, 1, block_k, d),
                        lambda b_, h, x, y: (b_, h // group, kj(x, y), 0))
        bias_spec = _spec((1, block_k), lambda b_, h, x, y: (b_, kj(x, y)))
        return [q_spec, q_spec, lse_spec, lse_spec,
                kv_spec, kv_spec, bias_spec]

    # pass 1: dk/dv/db — grid (…, kv, q), q innermost (accumulated over)
    dk_h, dv_h, db_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, hq, nk, nq),
        in_specs=in_specs(qi=lambda x, y: y, kj=lambda x, y: x),
        out_specs=[
            _spec((1, 1, block_k, d), lambda b_, h, x, y: (b_, h, x, 0)),
            _spec((1, 1, block_k, d), lambda b_, h, x, y: (b_, h, x, 0)),
            _spec((1, 1, block_k), lambda b_, h, x, y: (b_, h, x)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, lk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, lk), jnp.float32),
        ],
        interpret=interpret,
    )(q, dout, lse, delta, k, v, bias2d)

    # pass 2: dq — grid (…, q, kv), kv innermost (accumulated over)
    (dq,) = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, hq, nq, nk),
        in_specs=in_specs(qi=lambda x, y: x, kj=lambda x, y: y),
        out_specs=[
            _spec((1, 1, block_q, d), lambda b_, h, x, y: (b_, h, x, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, dout, lse, delta, k, v, bias2d)

    # per-query-head kv grads fold back onto the Hkv axis (GQA)
    dk = dk_h.reshape(b, hkv, group, lk, d).sum(axis=2)
    dv = dv_h.reshape(b, hkv, group, lk, d).sum(axis=2)
    dbias = db_h.sum(axis=1)                                   # [B, Lk]
    return dq, dk, dv, dbias


# ======================================================================
# custom-vjp core (static: causal/scale/blocks/interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd(
        q, k, v, bias2d, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, bias2d, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, dout):
    q, k, v, bias2d, out, lse = res
    dq, dk, dv, dbias = _bwd_call(
        q, k, v, bias2d, out, dout, lse,
        causal, scale, block_q, block_k, interpret,
    )
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        dbias.astype(bias2d.dtype),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


# ======================================================================
# public API


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array] = None,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention matching ``dot_product_attention`` semantics
    (transformer.py:105-133): q [B, Hq, L, Dh], k/v [B, Hkv, L, Dh],
    optional additive per-key ``bias`` [B, 1, 1, L], fp32 softmax,
    returns [B, Hq, L, Dh] in q's dtype. Differentiable via Pallas
    forward+backward kernels.

    Sequence lengths are padded to the block size internally (padded
    keys get -inf bias; padded query rows are sliced off), so any L
    works; multiples of 128 avoid the padding entirely.
    """
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    assert v.shape == k.shape
    if interpret is None:
        interpret = _default_interpret()
    scale = d ** -0.5

    if bias is None:
        bias2d = jnp.zeros((b, lk), jnp.float32)
    else:
        assert bias.shape == (b, 1, 1, lk), (
            f"bias must be [B,1,1,L], got {bias.shape}"
        )
        bias2d = bias.reshape(b, lk).astype(jnp.float32)

    if interpret:
        # CPU interpret mode: shrink blocks to the sequence so tiny test
        # shapes don't pay 128-padding
        block_q = min(block_q, _round_pow2(lq))
        block_k = min(block_k, _round_pow2(lk))
    else:
        # Real TPU lowering: blocks appear as the minor dim of the lse/db
        # tiles and the second-minor of the score tile, so keep them
        # (8, 128)-tile aligned — never below 128. Short sequences are
        # padded up to one block (padded keys carry -inf bias).
        block_q = max(128, min(block_q, _round_pow2(lq)))
        block_k = max(128, min(block_k, _round_pow2(lk)))
    pad_q = (-lq) % block_q
    pad_k = (-lk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        bias2d = jnp.pad(bias2d, ((0, 0), (0, pad_k)),
                         constant_values=NEG_INF)

    out = _flash(q, k, v, bias2d, causal, scale, block_q, block_k, interpret)
    if pad_q:
        out = out[:, :, :lq, :]
    return out


def _round_pow2(n: int) -> int:
    """Smallest power of two >= n (block size for short sequences)."""
    p = 1
    while p < n:
        p *= 2
    return p


def make_flash_attention_fn(block_q: int = 128, block_k: int = 128,
                            interpret: Optional[bool] = None):
    """Seam-compatible ``attention_fn`` (transformer.py:31-32) for any
    model in the zoo: ``model(..., attention_fn=make_flash_attention_fn())``."""

    def attention_fn(q, k, v, bias=None, causal=False):
        return flash_attention(
            q, k, v, bias=bias, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )

    return attention_fn
