"""Fused flash attention — the transformer zoo's hot op as a Pallas
TPU kernel.

The reference has no attention at all (its demo model is a 10→1 linear
layer, reference demo.py:15-49); this kernel exists for the model
families the new framework adds (BERT/Llama/ViT — BASELINE configs 3-5),
replacing the dense ``dot_product_attention`` einsum path
(baton_tpu/models/transformer.py) on the hot path:

* **never materializes the L×L score matrix in HBM** — scores live as
  one [block_q, block_k] VMEM tile at a time, with the online softmax
  (running max/sum rescaling) recurrence, so attention memory is
  O(L·Dh) instead of O(L²);
* **MXU-shaped**: every contraction is a ``jnp.dot`` with
  ``preferred_element_type=float32`` over 128-aligned tiles; softmax
  algebra rides the VPU in fp32 regardless of input dtype;
* **trains**: a custom VJP with a Pallas backward kernel recomputes
  p = exp(s − lse) blockwise from the saved logsumexp — the standard
  flash-attention backward — so the O(L²) probs are never stored for
  the backward pass either;
* **GQA for free**: the kv-head block index map sends query head ``h``
  to kv head ``h // (Hq//Hkv)`` — no ``jnp.repeat`` materialization;
* matches the seam contract ``attention_fn(q, k, v, bias, causal)``
  (transformer.py:31-32): additive per-key bias [B, 1, 1, L], static
  causal masking from global positions.

On CPU (tests, the 8-device virtual mesh) the kernel runs in Pallas
interpret mode — same code path, bit-compatible math.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces; absent on CPU-only builds of pallas
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None

NEG_INF = -1e30


def _spec(block, index_map):
    if _VMEM is None:
        return pl.BlockSpec(block, index_map)
    return pl.BlockSpec(block, index_map, memory_space=_VMEM)


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ======================================================================
# forward kernel: grid (B, Hq, Lq/block_q)


def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, nk):
    # Grid (B, Hq, Lq/bq, Lk/bk) with the kv axis INNERMOST ('arbitrary'):
    # the online-softmax state (acc/m/l) lives in VMEM scratch across the
    # j loop while Mosaic double-buffers the k/v block DMAs — the r2
    # whole-K/V-per-program version re-fetched all of K/V from HBM for
    # every q block (nq× traffic) and could not overlap DMA with compute.
    # m/l are (bq, 128) lane-broadcast: TPU vector layout wants the minor
    # dim lane-aligned, so the scalar-per-row state rides 128 lanes.
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: a kv block strictly in every query's future contributes
    # nothing — skip its matmuls (≈half the FLOPs on average)
    needed = True
    if causal:
        needed = j * block_k <= i * block_q + (block_q - 1)

    @pl.when(needed)
    def _accumulate():
        # operands stay in the input dtype (bf16 on the bf16 path): the
        # MXU multiplies bf16 natively with fp32 accumulation via
        # preferred_element_type — upcasting first would force 4-8x
        # slower fp32 MXU passes. Softmax statistics are fp32 throughout.
        q = q_ref[...]                                   # [bq, D]
        kj = k_ref[...]                                  # [bk, D]
        vj = v_ref[...]
        # contract D via dot_general — an explicit kj.T would force a
        # Mosaic relayout before the MXU op
        s = lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s = s + b_ref[...]                               # [1, bk] bias
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_ref[:, :1]                            # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(vj.dtype), vj, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        m = m_ref[:, :1]
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0, :] = (m + jnp.log(l))[:, 0]


def _compiler_params(n_parallel: int):
    """Mark the leading grid axes parallel, the innermost sequential."""
    if _VMEM is None:  # pragma: no cover
        return None
    semantics = ("parallel",) * n_parallel + ("arbitrary",)
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    return cls(dimension_semantics=semantics) if cls else None


def _scratch(shape, dtype=jnp.float32):
    if _VMEM is None:  # pragma: no cover
        raise RuntimeError("pallas TPU memory spaces unavailable")
    return _VMEM(shape, dtype)


def _fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    group = hq // hkv
    nk = lk // block_k
    grid = (b, hq, lq // block_q, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _spec((None, None, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            _spec((None, None, block_k, d),
                  lambda b_, h, i, j: (b_, h // group, j, 0)),
            _spec((None, None, block_k, d),
                  lambda b_, h, i, j: (b_, h // group, j, 0)),
            _spec((None, 1, block_k), lambda b_, h, i, j: (b_, 0, j)),
        ],
        out_specs=[
            _spec((None, None, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            _spec((None, None, 1, block_q), lambda b_, h, i, j: (b_, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, 1, lq), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        compiler_params=None if interpret else _compiler_params(3),
        interpret=interpret,
    )(q, k, v, bias2d.reshape(b, 1, lk))
    return out, lse.reshape(b, hq, lq)


# ======================================================================
# backward: the standard two-pass flash-attention backward, blockwise
# recompute of p from the saved lse (no O(L²) residuals). Pass 1 grids
# (B, Hq, Lk/block_k, Lq/block_q) and accumulates dk/dv/db over the
# innermost q axis; pass 2 grids (B, Hq, Lq/block_q, Lk/block_k) and
# accumulates dq over the innermost kv axis. Only block-sized tiles are
# ever VMEM-resident, so VMEM is O(block²), independent of L (the r1
# single-program-per-head version held ~7 full [L, d] buffers).
# delta = rowsum(do·o) is precomputed outside pallas.


def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, b_ref,
                    dk_ref, dv_ref, db_ref, *, scale, causal,
                    block_q, block_k):
    j = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref[...])
        dv_ref[...] = jnp.zeros_like(dv_ref[...])
        db_ref[...] = jnp.zeros_like(db_ref[...])

    qi = q_ref[...]                                            # [bq, D]
    doi = do_ref[...]                                          # [bq, D]
    lsei = lse_ref[0][:, None]                                 # [bq, 1]
    delta = delta_ref[0][:, None]                              # [bq, 1]
    kj = k_ref[...]                                            # [bk, D]
    vj = v_ref[...]
    bj = b_ref[...]                                            # [1, bk]

    s = (lax.dot_general(
        qi, kj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bj)
    if causal:
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lsei)                                      # [bq, bk]
    dp = lax.dot_general(
        doi, vj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta)                                      # [bq, bk]
    # contract the bq axis directly (p^T·do, ds^T·q without transposes)
    dv_ref[...] += lax.dot_general(
        p.astype(doi.dtype), doi, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dk_ref[...] += scale * lax.dot_general(
        ds.astype(qi.dtype), qi, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    db_ref[...] += ds.sum(axis=0)[None, :]


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref, b_ref,
                   dq_ref, *, scale, causal, block_q, block_k):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref[...])

    qi = q_ref[...]
    doi = do_ref[...]
    lsei = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    kj = k_ref[...]
    vj = v_ref[...]
    bj = b_ref[...]

    s = (lax.dot_general(
        qi, kj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bj)
    if causal:
        q_pos = i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lsei)
    dp = lax.dot_general(
        doi, vj, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta)
    dq_ref[...] += scale * jnp.dot(
        ds.astype(kj.dtype), kj, preferred_element_type=jnp.float32
    )


def _bwd_call(q, k, v, bias2d, out, dout, lse,
              causal, scale, block_q, block_k, interpret):
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    group = hq // hkv
    nq, nk = lq // block_q, lk // block_k

    # delta [B, Hq, Lq] in fp32 — cheap elementwise reduce, let XLA fuse it
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    # low-rank operands get an explicit size-1 second-minor dim so their
    # kept last-two block dims satisfy Mosaic's (8, 128) tiling rule
    lse4 = lse.reshape(b, hq, 1, lq)
    delta4 = delta.reshape(b, hq, 1, lq)
    bias3 = bias2d.reshape(b, 1, lk)

    def in_specs(qi, kj):
        """Common input specs; ``qi``/``kj`` pick the q/kv block index out
        of the two trailing grid axes (x, y)."""
        q_spec = _spec((None, None, block_q, d),
                       lambda b_, h, x, y: (b_, h, qi(x, y), 0))
        lse_spec = _spec((None, None, 1, block_q),
                         lambda b_, h, x, y: (b_, h, 0, qi(x, y)))
        kv_spec = _spec((None, None, block_k, d),
                        lambda b_, h, x, y: (b_, h // group, kj(x, y), 0))
        bias_spec = _spec((None, 1, block_k),
                          lambda b_, h, x, y: (b_, 0, kj(x, y)))
        return [q_spec, q_spec, lse_spec, lse_spec,
                kv_spec, kv_spec, bias_spec]

    # pass 1: dk/dv/db — grid (…, kv, q), q innermost (accumulated over)
    dk_h, dv_h, db_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, hq, nk, nq),
        in_specs=in_specs(qi=lambda x, y: y, kj=lambda x, y: x),
        out_specs=[
            _spec((None, None, block_k, d), lambda b_, h, x, y: (b_, h, x, 0)),
            _spec((None, None, block_k, d), lambda b_, h, x, y: (b_, h, x, 0)),
            _spec((None, None, 1, block_k), lambda b_, h, x, y: (b_, h, 0, x)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, lk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 1, lk), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(3),
        interpret=interpret,
    )(q, dout, lse4, delta4, k, v, bias3)

    # pass 2: dq — grid (…, q, kv), kv innermost (accumulated over)
    (dq,) = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, hq, nq, nk),
        in_specs=in_specs(qi=lambda x, y: x, kj=lambda x, y: y),
        out_specs=[
            _spec((None, None, block_q, d), lambda b_, h, x, y: (b_, h, x, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, lq, d), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(3),
        interpret=interpret,
    )(q, dout, lse4, delta4, k, v, bias3)

    # per-query-head kv grads fold back onto the Hkv axis (GQA)
    dk = dk_h.reshape(b, hkv, group, lk, d).sum(axis=2)
    dv = dv_h.reshape(b, hkv, group, lk, d).sum(axis=2)
    dbias = db_h[:, :, 0].sum(axis=1)                          # [B, Lk]
    return dq, dk, dv, dbias


# ======================================================================
# custom-vjp core (static: causal/scale/blocks/interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, bias2d, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd(
        q, k, v, bias2d, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, bias2d, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, dout):
    q, k, v, bias2d, out, lse = res
    dq, dk, dv, dbias = _bwd_call(
        q, k, v, bias2d, out, dout, lse,
        causal, scale, block_q, block_k, interpret,
    )
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
        dbias.astype(bias2d.dtype),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


# ======================================================================
# public API


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array] = None,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention matching ``dot_product_attention`` semantics
    (transformer.py:105-133): q [B, Hq, L, Dh], k/v [B, Hkv, L, Dh],
    optional additive per-key ``bias`` [B, 1, 1, L], fp32 softmax,
    returns [B, Hq, L, Dh] in q's dtype. Differentiable via Pallas
    forward+backward kernels.

    Sequence lengths are padded to the block size internally (padded
    keys get -inf bias; padded query rows are sliced off), so any L
    works; multiples of 128 avoid the padding entirely.
    """
    b, hq, lq, d = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    assert hq % hkv == 0, f"GQA needs Hq % Hkv == 0, got {hq} % {hkv}"
    assert v.shape == k.shape
    if interpret is None:
        interpret = _default_interpret()
    scale = d ** -0.5

    if bias is None:
        bias2d = jnp.zeros((b, lk), jnp.float32)
    else:
        assert bias.shape == (b, 1, 1, lk), (
            f"bias must be [B,1,1,L], got {bias.shape}"
        )
        bias2d = bias.reshape(b, lk).astype(jnp.float32)

    block_q, block_k, pad_q, pad_k = _prepare_padding(
        lq, lk, block_q, block_k, interpret
    )
    q = _pad_len(q, pad_q)
    k, v = _pad_len(k, pad_k), _pad_len(v, pad_k)
    bias2d = _pad_bias2d(bias2d, pad_k)

    out = _flash(q, k, v, bias2d, causal, scale, block_q, block_k, interpret)
    if pad_q:
        out = out[:, :, :lq, :]
    return out


def _prepare_padding(lq, lk, block_q, block_k, interpret):
    """Clamped blocks + the q/k pad amounts for them (shared by the
    public kernel and the ring block entry points)."""
    block_q, block_k = _pick_blocks(lq, lk, block_q, block_k, interpret)
    return block_q, block_k, (-lq) % block_q, (-lk) % block_k


def _pad_len(x, pad):
    """Zero-pad the sequence axis (2) of a [B, H, L, D] tensor."""
    if not pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def _pad_bias2d(bias2d, pad):
    """-inf-pad the key axis of a [B, L] bias: padded keys attend nothing."""
    if not pad:
        return bias2d
    return jnp.pad(bias2d, ((0, 0), (0, pad)), constant_values=NEG_INF)


def _round_pow2(n: int) -> int:
    """Smallest power of two >= n (block size for short sequences)."""
    p = 1
    while p < n:
        p *= 2
    return p


# ======================================================================
# block-level entry points for sequence-parallel composition
# (parallel/ring_attention.py::flash_ring_attention): one K/V block's
# flash forward returning the normalized output AND the logsumexp (for
# cross-block online combination), and the matching backward given the
# GLOBAL out/lse — the standard ring-attention decomposition, where each
# block's backward against full-softmax statistics yields exactly its
# contribution to the global gradients.


def _pick_blocks(lq, lk, block_q, block_k, interpret):
    """Clamp requested block sizes to the sequence. Interpret mode (CPU
    tests) shrinks to the pow2 sequence so tiny shapes don't pay
    128-padding; real TPU lowering keeps blocks >= 128 — they appear as
    the minor dim of the lse/db tiles and the second-minor of the score
    tile, so they must stay (8, 128)-tile aligned (short sequences pad
    up to one block, padded keys carrying -inf bias)."""
    if interpret:
        return (min(block_q, _round_pow2(lq)),
                min(block_k, _round_pow2(lk)))
    return (max(128, min(block_q, _round_pow2(lq))),
            max(128, min(block_k, _round_pow2(lk))))


def flash_block_fwd(q, k, v, bias2d, causal, block_q=512, block_k=1024,
                    interpret=None):
    """One block's flash forward: (out [B,Hq,Lq,D] normalized, lse
    [B,Hq,Lq] fp32). ``bias2d`` is the per-key additive bias [B, Lk].
    NOT differentiable — pair with :func:`flash_block_bwd` inside an
    outer custom VJP."""
    b, hq, lq, d = q.shape
    lk = k.shape[2]
    if interpret is None:
        interpret = _default_interpret()
    scale = d ** -0.5
    block_q, block_k, pad_q, pad_k = _prepare_padding(
        lq, lk, block_q, block_k, interpret
    )
    q = _pad_len(q, pad_q)
    k, v = _pad_len(k, pad_k), _pad_len(v, pad_k)
    bias2d = _pad_bias2d(bias2d, pad_k)
    out, lse = _fwd(q, k, v, bias2d.astype(jnp.float32), causal, scale,
                    block_q, block_k, interpret)
    if pad_q:
        out = out[:, :, :lq, :]
        lse = lse[:, :, :lq]
    return out, lse


def flash_block_bwd(q, k, v, bias2d, out, dout, lse, causal,
                    block_q=512, block_k=1024, interpret=None):
    """One block's flash backward against GLOBAL (out, lse): returns
    (dq, dk, dv, dbias2d) — this block's exact contributions to the
    global gradients."""
    b, hq, lq, d = q.shape
    lk = k.shape[2]
    if interpret is None:
        interpret = _default_interpret()
    scale = d ** -0.5
    block_q, block_k, pad_q, pad_k = _prepare_padding(
        lq, lk, block_q, block_k, interpret
    )
    q = _pad_len(q, pad_q)
    out = _pad_len(out, pad_q)
    dout = _pad_len(dout, pad_q)  # zero dout rows => zero grads
    if pad_q:
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)))
    k, v = _pad_len(k, pad_k), _pad_len(v, pad_k)
    bias2d = _pad_bias2d(bias2d, pad_k)
    dq, dk, dv, dbias = _bwd_call(
        q, k, v, bias2d.astype(jnp.float32), out, dout, lse,
        causal, scale, block_q, block_k, interpret,
    )
    if pad_q:
        dq = dq[:, :, :lq, :]
    if pad_k:
        dk = dk[:, :, : lk, :]
        dv = dv[:, :, : lk, :]
        dbias = dbias[:, :lk]
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias
    )


def make_flash_attention_fn(block_q: int = 512, block_k: int = 1024,
                            interpret: Optional[bool] = None):
    """Seam-compatible ``attention_fn`` (transformer.py:31-32) for any
    model in the zoo: ``model(..., attention_fn=make_flash_attention_fn())``."""

    def attention_fn(q, k, v, bias=None, causal=False):
        return flash_attention(
            q, k, v, bias=bias, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )

    return attention_fn
