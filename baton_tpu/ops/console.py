"""Live fleet ops console: ``python -m baton_tpu.ops``.

Polls the root manager and any number of edges over plain HTTP —
``GET …/metrics``, ``GET …/fleet/health`` — plus (optionally) the
manager's ``rounds.jsonl``, and renders a top-like terminal view:
round throughput, per-tier phase counters, the compute plane (per-node
MFU, samples/sec/chip, peak HBM, recompile-storm flag from the
``compute_*`` gauges), and every known client with its fleet-health
classification (healthy / slow / flaky / degrading / inactive) and the
reason string the anomaly scorer produced.

Live mode polls metrics history as a DELTA: each refresh passes the
previous poll's ``ts`` as ``/metrics/history?since=<ts>`` so only new
samples cross the wire, never the full ring.

Two modes:

- **live** (default): clear-screen redraw every ``--interval`` seconds
  until interrupted — the operator's ``top`` for a federation.
- **``--once --json``**: one poll, machine-readable JSON on stdout
  (including each node's ``/alerts`` state), exit 0 only when every
  polled node answered AND no ``severity: page`` alert is firing
  anywhere in the fleet — usable as a CI smoke probe
  (``scripts/smoke_trace.py`` runs exactly this).

stdlib-only on purpose (``urllib``, no aiohttp, no asyncio): the
console must work from any operator shell that can ``python -m``, even
one without the serving stack's event-loop context.

URLs name the experiment base, e.g. ``http://127.0.0.1:8473/fedmodel``
— the console appends ``/metrics`` and ``/fleet/health`` itself.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = ["fetch_json", "poll_node", "poll_fleet", "firing_alerts",
           "render", "main"]

#: severity order for the client table (worst first)
_STATUS_ORDER = {"slow": 0, "flaky": 1, "degrading": 2, "healthy": 3,
                 "inactive": 4}
_STATUS_COLOR = {"slow": "\x1b[31m", "flaky": "\x1b[35m",
                 "degrading": "\x1b[33m", "healthy": "\x1b[32m",
                 "inactive": "\x1b[2m"}
_RESET = "\x1b[0m"


def fetch_json(url: str, timeout_s: float = 3.0) -> Optional[dict]:
    """GET one JSON document; None on any transport/decode failure —
    a dead node is a *row* in the console, never a crash."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError):
        return None


def poll_node(
    base_url: str,
    timeout_s: float = 3.0,
    history_since: Optional[float] = None,
) -> dict:
    """One node's ``/metrics`` + ``/fleet/health``, tagged with
    reachability (``up``) so the renderer can show dead tiers.
    ``history_since`` additionally fetches the metrics-history DELTA
    (``/metrics/history?since=<ts>``) — only samples newer than the
    previous poll, never the full ring."""
    base = base_url.rstrip("/")
    metrics = fetch_json(f"{base}/metrics", timeout_s)
    health = fetch_json(f"{base}/fleet/health", timeout_s)
    out = {
        "url": base,
        "up": metrics is not None,
        "metrics": metrics,
        "health": health,
        # alerting plane (None against a pre-alerts node — renderable)
        "alerts": fetch_json(f"{base}/alerts", timeout_s),
        # runbook/actuation plane (None against a pre-runbooks node)
        "runbooks": fetch_json(f"{base}/runbooks", timeout_s),
        # replication plane (None against a non-HA node — renderable)
        "replication": fetch_json(f"{base}/replication", timeout_s),
    }
    if history_since is not None:
        out["history"] = fetch_json(
            f"{base}/metrics/history?since={history_since:.6f}", timeout_s
        )
    return out


def _tail_rounds(path: Optional[str], n: int = 5) -> List[dict]:
    if not path:
        return []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
    except OSError:
        return []
    out: List[dict] = []
    for line in lines[-n:]:
        try:
            out.append(json.loads(line))
        except ValueError:
            continue  # torn final line from a crash mid-append
    return out


def poll_fleet(
    root: str,
    edges: List[str],
    rounds_path: Optional[str] = None,
    timeout_s: float = 3.0,
    history_since: Optional[float] = None,
) -> dict:
    """The full console state for one poll — also the ``--json``
    payload, so the interactive view and the CI probe can never
    drift apart. ``history_since`` (the previous poll's ``ts``) makes
    the root poll fetch only new metrics-history samples."""
    return {
        "ts": round(time.time(), 3),
        "root": poll_node(root, timeout_s, history_since=history_since),
        "edges": [poll_node(e, timeout_s) for e in edges],
        "rounds_tail": _tail_rounds(rounds_path),
    }


# -- rendering ---------------------------------------------------------
def _fmt_s(v: Any) -> str:
    if isinstance(v, (int, float)):
        return f"{v:8.3f}s"
    return "       --"


def _counter(node: dict, name: str) -> float:
    m = node.get("metrics") or {}
    return float((m.get("counters") or {}).get(name, 0.0))


def _gauge(node: dict, name: str) -> Optional[float]:
    m = node.get("metrics") or {}
    v = (m.get("gauges") or {}).get(name)
    return float(v) if isinstance(v, (int, float)) else None


def _fmt_num(v: Any, fmt: str = "{:.3f}") -> str:
    if isinstance(v, (int, float)):
        return fmt.format(v)
    return "--"


def _compute_line(node: dict, label: str) -> Optional[str]:
    """The per-node compute pane row: last-round MFU / throughput /
    HBM gauges plus the recompile-storm flag. None when the node has
    never published a compute gauge (pre-compute managers stay
    renderable)."""
    mfu = _gauge(node, "compute_mfu")
    sps = _gauge(node, "compute_samples_per_sec_per_chip")
    hbm = _gauge(node, "compute_peak_hbm_gb")
    steps = _gauge(node, "compute_steps")
    reporters = _gauge(node, "compute_reporters")
    storm = _gauge(node, "compute_recompile_storm")
    if all(v is None for v in (mfu, sps, hbm, steps, reporters, storm)):
        return None
    storm_s = "STORM" if storm else "no"
    return (
        f"  compute[{label}]: mfu={_fmt_num(mfu)}  "
        f"sps/chip={_fmt_num(sps, '{:.1f}')}  "
        f"hbm={_fmt_num(hbm, '{:.2f}')}GiB  "
        f"steps={_fmt_num(steps, '{:.0f}')}  "
        f"reporters={_fmt_num(reporters, '{:.0f}')}  "
        f"recompile-storm={storm_s}"
    )


def _replication_line(node: dict, label: str) -> Optional[str]:
    """The per-node replication pane row: role / epoch / WAL positions
    / standby lag. None when the node has no ``/replication`` endpoint
    or HA is not configured (pre-replication managers stay
    renderable)."""
    rep = node.get("replication")
    if not isinstance(rep, dict) or rep.get("role") is None:
        return None
    role = str(rep.get("role", "?"))
    epoch = rep.get("epoch")
    wal = rep.get("wal") or {}
    parts = [
        f"  replication[{label}]: role={role}",
        f"epoch={_fmt_num(epoch, '{:.0f}')}",
    ]
    if role == "active":
        targets = wal.get("targets") or {}
        shipped = wal.get("min_shipped_offset")
        parts.append(f"standbys={len(targets)}")
        parts.append(f"shipped_offset={_fmt_num(shipped, '{:.0f}')}")
    else:
        parts.append(
            f"applied_offset={_fmt_num(wal.get('applied_offset'), '{:.0f}')}")
        parts.append(f"lag={_fmt_num(wal.get('lag_s'))}s")
    lease = rep.get("lease") or {}
    if lease:
        parts.append(f"lease_holder={lease.get('holder', '?')}")
    return "  ".join(parts)


def firing_alerts(state: dict, severity: Optional[str] = None) -> List[dict]:
    """Every firing alert across the polled fleet (root + edges),
    optionally filtered by severity — the CI probe's page check and the
    alert pane share this one extractor."""
    out: List[dict] = []
    for node in [state["root"]] + list(state["edges"]):
        alerts = node.get("alerts") or {}
        for rule in alerts.get("rules") or []:
            if rule.get("state") != "firing":
                continue
            if severity is not None and rule.get("severity") != severity:
                continue
            out.append(dict(rule, node=alerts.get("node", node["url"])))
    return out


def _alert_pane(state: dict, paint) -> List[str]:
    """The alert pane: firing rules first (page severity painted red),
    then pending ones; silent when the whole fleet is quiet."""
    lines: List[str] = []
    rows: List[tuple] = []
    for node in [state["root"]] + list(state["edges"]):
        alerts = node.get("alerts") or {}
        label = alerts.get("node", node["url"])
        for rule in alerts.get("rules") or []:
            if rule.get("state") in ("firing", "pending"):
                rows.append((0 if rule["state"] == "firing" else 1,
                             label, rule))
    if not rows:
        return lines
    rows.sort(key=lambda r: (r[0], r[1], r[2].get("name", "")))
    lines.append("  alerts:")
    for _, label, rule in rows:
        sev = rule.get("severity", "warn")
        text = (
            f"    {rule.get('state', '?').upper():<8} "
            f"[{sev}] {label}: {rule.get('name')} "
            f"({rule.get('metric')} {rule.get('op')} "
            f"{rule.get('threshold')}; value={rule.get('value')}, "
            f"episodes={rule.get('episodes', 0)})"
        )
        if rule.get("state") == "firing":
            text = paint("slow" if sev == "page" else "degrading", text)
        lines.append(text)
    return lines


def _runbook_pane(state: dict, paint) -> List[str]:
    """The actuations pane: ACTIVE remediations first (painted — an
    actuating fleet is a fleet being steered), then pending ones, each
    with its trigger and how many times the manager applied it; silent
    when no rule is loaded or everything is idle."""
    lines: List[str] = []
    rows: List[tuple] = []
    for node in [state["root"]] + list(state["edges"]):
        rb = node.get("runbooks") or {}
        label = rb.get("node", node["url"])
        for rule in rb.get("rules") or []:
            if rule.get("state") in ("active", "pending"):
                rows.append((0 if rule["state"] == "active" else 1,
                             label, rule))
    if not rows:
        return lines
    rows.sort(key=lambda r: (r[0], r[1], r[2].get("name", "")))
    lines.append("  actuations:")
    for _, label, rule in rows:
        text = (
            f"    {rule.get('state', '?').upper():<8} "
            f"{label}: {rule.get('name')} -> {rule.get('action')} "
            f"(on {rule.get('trigger')}; value={rule.get('value')}, "
            f"applied={rule.get('actuations', 0)}x, "
            f"episodes={rule.get('episodes', 0)})"
        )
        if rule.get("state") == "active":
            text = paint("degrading", text)
        lines.append(text)
    return lines


def _client_rows(health: Optional[dict], via: str) -> List[tuple]:
    rows = []
    for cid, info in ((health or {}).get("clients") or {}).items():
        rows.append((
            _STATUS_ORDER.get(info.get("status"), 9), cid, via, info
        ))
    return rows


def render(state: dict, color: bool = True) -> str:
    """One frame of the top-like view as a string (the caller owns the
    clear-screen escape so tests can snapshot frames)."""

    def paint(status: str, text: str) -> str:
        if not color:
            return text
        return f"{_STATUS_COLOR.get(status, '')}{text}{_RESET}"

    root = state["root"]
    lines: List[str] = []
    stamp = time.strftime("%H:%M:%S", time.localtime(state["ts"]))
    up = "up" if root["up"] else paint("slow", "DOWN")
    lines.append(
        f"baton fleet console  {stamp}  root={root['url']} [{up}]  "
        f"edges={sum(1 for e in state['edges'] if e['up'])}"
        f"/{len(state['edges'])} up"
    )
    lines.append(
        f"  rounds finished={_counter(root, 'rounds_finished'):.0f}  "
        f"updates={_counter(root, 'updates_received'):.0f}  "
        f"edge partials={_counter(root, 'updates_received_edge_partial'):.0f}  "
        f"fleet obs={_counter(root, 'fleet_observations'):.0f}"
    )
    for e in state["edges"]:
        phases = "  ".join(
            f"{k.split('edge_phase_')[-1]}={_counter(e, k):.2f}s"
            for k in ("edge_phase_fold_s", "edge_phase_settle_s")
        ) if e["up"] else "unreachable"
        node = ((e.get("health") or {}).get("node")) or e["url"]
        mark = "" if e["up"] else " [DOWN]"
        lines.append(f"  {node}{mark}: "
                     f"folded={_counter(e, 'edge_updates_folded'):.0f}  "
                     f"shipped={_counter(e, 'edge_partials_shipped'):.0f}  "
                     f"{phases}")

    compute_rows = [_compute_line(root, "root")]
    for e in state["edges"]:
        node = ((e.get("health") or {}).get("node")) or e["url"]
        compute_rows.append(_compute_line(e, node))
    compute_rows = [r for r in compute_rows if r]
    if compute_rows:
        storming = any("STORM" in r for r in compute_rows)
        lines.extend(paint("slow", r) if ("STORM" in r and color) else r
                     for r in compute_rows)
        if storming:
            lines.append(paint("slow", "  !! recompile storm in the "
                                       "last round — check input "
                                       "shape churn"))

    rep_line = _replication_line(root, "root")
    if rep_line:
        lines.append(rep_line)

    alert_lines = _alert_pane(state, paint)
    if alert_lines:
        lines.extend(alert_lines)
    runbook_lines = _runbook_pane(state, paint)
    if runbook_lines:
        lines.extend(runbook_lines)

    summary = ((root.get("health") or {}).get("summary")) or {}
    if summary:
        lines.append(
            "  health: " + "  ".join(
                paint(k, f"{k}={summary.get(k, 0)}")
                for k in ("healthy", "slow", "flaky", "degrading",
                          "inactive")
            ) + f"  total={summary.get('total', 0)}"
        )
    lines.append("")
    lines.append(f"  {'CLIENT':<28} {'VIA':<10} {'STATUS':<10} "
                 f"{'TRAIN':>9} {'ROUNDS':>6} {'MISS':>4}  REASON")
    rows = _client_rows(root.get("health"), "root")
    for e in state["edges"]:
        rows += _client_rows(e.get("health"),
                             ((e.get("health") or {}).get("node")) or "edge")
    rows.sort(key=lambda r: (r[0], r[1]))
    for _, cid, via, info in rows:
        status = info.get("status", "?")
        lines.append(
            f"  {cid:<28.28} {via:<10.10} "
            + paint(status, f"{status:<10}")
            + f" {_fmt_s(info.get('train_s_median'))}"
            f" {info.get('rounds_seen', 0):>6}"
            f" {info.get('missed', 0):>4}"
            f"  {info.get('reason', '')}"
        )
    tail = state.get("rounds_tail") or []
    if tail:
        lines.append("")
        lines.append("  recent rounds:")
        for r in tail:
            why = r.get("straggler_why") or {}
            why_s = ("  why: " + "; ".join(
                f"{c}: {w}" for c, w in sorted(why.items())
            )) if why else ""
            comp = r.get("compute") or {}
            comp_s = ""
            if isinstance(comp, dict) and comp:
                comp_s = (
                    f"  mfu={_fmt_num(comp.get('mfu'))}"
                    f" sps/chip="
                    f"{_fmt_num(comp.get('samples_per_sec_per_chip'), '{:.1f}')}"
                    f" compile={_fmt_num(comp.get('compile_s'))}s"
                )
                if comp.get("recompile_storms"):
                    comp_s += f" storms={comp['recompile_storms']}"
            lines.append(
                f"    {r.get('round')}: {r.get('outcome')} "
                f"{float(r.get('duration_s') or 0.0):.2f}s "
                f"reporters={r.get('reporters')}"
                f"/{r.get('participants')}{why_s}{comp_s}"
            )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m baton_tpu.ops",
        description="live fleet health console (root + edges)",
    )
    ap.add_argument("--root", required=True,
                    help="experiment base URL, e.g. "
                         "http://127.0.0.1:8473/fedmodel")
    ap.add_argument("--edges", default="",
                    help="comma-separated edge base URLs")
    ap.add_argument("--rounds", default=None,
                    help="path to the manager's rounds.jsonl (optional; "
                         "adds the recent-rounds pane)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval in live mode (default 2s)")
    ap.add_argument("--timeout", type=float, default=3.0,
                    help="per-request HTTP timeout")
    ap.add_argument("--once", action="store_true",
                    help="poll once and exit (exit 1 if a node is down)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw poll state as JSON (implies no "
                         "ANSI); with --once this is the CI probe mode")
    args = ap.parse_args(argv)

    edges = [e.strip() for e in args.edges.split(",") if e.strip()]
    last_ts: Optional[float] = None
    while True:
        state = poll_fleet(args.root, edges, args.rounds, args.timeout,
                           history_since=last_ts)
        last_ts = state["ts"]
        all_up = state["root"]["up"] and all(
            e["up"] for e in state["edges"]
        )
        if args.as_json:
            print(json.dumps(state, indent=2, default=repr))
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(render(state, color=sys.stdout.isatty()))
        if args.once:
            # the CI probe fails on a dead node OR a firing page-severity
            # alert anywhere in the fleet — liveness alone is not health
            pages = firing_alerts(state, severity="page")
            if pages and not args.as_json:
                for rule in pages:
                    print(f"PAGE firing: {rule.get('node')}: "
                          f"{rule.get('name')}")
            return 0 if (all_up and not pages) else 1
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
