"""Ragged-data padding — static shapes for XLA, exact counts for FedAvg.

Clients hold different amounts of data (the reference demo draws
``32·randint(5,20)`` samples per client per round, demo.py:52-59). XLA
wants static shapes, and the sample-weighted FedAvg math wants *exact*
per-client counts (manager.py:119-126). The contract: every client
dataset is padded (with zeros) to a shared ``capacity`` divisible by the
batch size, and the true row count travels alongside as ``n_samples``.
Validity masks are derived from ``n_samples`` inside the jitted trainer,
so padding never contributes to losses, gradients, or aggregation
weights.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import numpy as np


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def pad_to_capacity(array: np.ndarray, capacity: int) -> np.ndarray:
    """Zero-pad axis 0 of ``array`` to ``capacity`` rows."""
    n = array.shape[0]
    if n > capacity:
        raise ValueError(f"dataset has {n} rows > capacity {capacity}")
    if n == capacity:
        return array
    pad = np.zeros((capacity - n,) + array.shape[1:], dtype=array.dtype)
    return np.concatenate([array, pad], axis=0)


def pad_dataset(
    data: Dict[str, np.ndarray], capacity: int
) -> Tuple[Dict[str, np.ndarray], int]:
    """Pad every array in ``data`` to ``capacity`` rows; returns
    ``(padded, n_samples)``."""
    n = next(iter(data.values())).shape[0]
    padded = {k: pad_to_capacity(np.asarray(v), capacity) for k, v in data.items()}
    return padded, n


def stack_client_datasets(
    datasets: Sequence[Dict[str, np.ndarray]],
    batch_size: int,
    capacity: int | None = None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Pad + stack per-client datasets into ``[C, capacity, ...]`` arrays.

    Returns ``(stacked_data, n_samples[C])`` — the layout the simulation
    engine vmaps/shards over. ``capacity`` defaults to the largest client
    dataset rounded up to a batch multiple.
    """
    if not datasets:
        raise ValueError("no client datasets")
    sizes = [next(iter(d.values())).shape[0] for d in datasets]
    if capacity is None:
        capacity = round_up(max(sizes), batch_size)
    else:
        capacity = round_up(capacity, batch_size)
    keys = list(datasets[0].keys())
    stacked = {
        k: np.stack([pad_to_capacity(np.asarray(d[k]), capacity) for d in datasets])
        for k in keys
    }
    return stacked, np.asarray(sizes, dtype=np.int32)
