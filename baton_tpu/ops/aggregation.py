"""Aggregation kernels — sample-weighted FedAvg, the TPU way.

The reference aggregates on the manager in Python: for every state_dict
key it computes ``Σ(client_tensor · n_samples) / Σ n_samples`` with an
in-place write (reference: manager.py:113-132). That per-key Python loop
is the aggregation hot loop (SURVEY §3.2).

Here the same math is a single fused XLA program:

* stacked form — client params as a leading axis ``[C, ...]`` on every
  leaf, aggregation a ``tensordot`` with the weight vector (rides the
  MXU for large leaves);
* mesh form — under ``shard_map`` over a ``Mesh(('clients',))`` each
  shard reduces its local clients then ``psum``s the weighted sums and
  the weight total over ICI (:func:`psum_weighted_mean`). Two psums of
  equal-shaped trees; XLA fuses them into one collective per leaf.

The unit-test oracle is the reference formula evaluated in numpy
(SURVEY §4c).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Params = Any


def tree_stack(trees: Sequence[Params]) -> Params:
    """Stack a list of identically-structured pytrees along a new axis 0."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: Params) -> list:
    """Inverse of :func:`tree_stack` (host-side; for the HTTP edge)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = leaves[0].shape[0]
    return [
        jax.tree_util.tree_unflatten(treedef, [leaf[i] for leaf in leaves])
        for i in range(n)
    ]


def weighted_tree_sum(stacked: Params, weights: jax.Array) -> Params:
    """``Σ_c w_c · leaf[c]`` for every leaf of a ``[C, ...]``-stacked tree.

    Returns fp32 leaves regardless of input dtype: these are partial
    sums destined for further accumulation (waves, psum) — casting back
    to bf16/fp16 here would lose the fp32 accumulation guarantee and can
    overflow fp16 at realistic sample counts. Callers cast the final
    mean back to the param dtype.
    """
    w = weights.astype(jnp.float32)

    def one(leaf):
        return jnp.tensordot(w, leaf.astype(jnp.float32), axes=(0, 0))

    return jax.tree_util.tree_map(one, stacked)


def weighted_tree_mean(stacked: Params, weights: jax.Array) -> Params:
    """Sample-weighted FedAvg over a stacked client axis.

    Exactly the reference manager's update rule
    ``value = Σ(client_value · n_samples) / Σ n_samples``
    (manager.py:123-126), computed in fp32 regardless of param dtype to
    avoid bf16 accumulation error at large client counts.
    """
    w = weights.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1e-9)

    def one(leaf):
        s = jnp.tensordot(w, leaf.astype(jnp.float32), axes=(0, 0))
        return (s / denom).astype(leaf.dtype)

    return jax.tree_util.tree_map(one, stacked)


def psum_weighted_mean(
    local_stacked: Params, local_weights: jax.Array, axis_name: str
) -> Params:
    """FedAvg across a sharded client axis, inside ``shard_map``.

    Each device holds ``[C_local, ...]`` client params and their sample
    weights; the global weighted mean is two ICI collectives:
    ``psum(Σ_local w·p)`` and ``psum(Σ_local w)``. This is the TPU-native
    replacement for the reference's HTTP gather + Python loop
    (SURVEY §5 "Distributed communication backend").
    """
    w = local_weights.astype(jnp.float32)
    local_sums = weighted_tree_sum(
        jax.tree_util.tree_map(lambda l: l.astype(jnp.float32), local_stacked), w
    )
    global_sums = jax.lax.psum(local_sums, axis_name)
    global_w = jax.lax.psum(jnp.sum(w), axis_name)
    denom = jnp.maximum(global_w, 1e-9)
    return jax.tree_util.tree_map(lambda s: s / denom, global_sums)


def weighted_scalar_mean(values: jax.Array, weights: jax.Array) -> jax.Array:
    """Sample-weighted mean of per-client scalars/vectors (loss history
    aggregation — reference manager.py:127-130). values [C, ...]."""
    w = weights.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1e-9)
    return jnp.tensordot(w, values.astype(jnp.float32), axes=(0, 0)) / denom


def tree_sub(a: Params, b: Params) -> Params:
    return jax.tree_util.tree_map(lambda x, y: x - y, a, b)


def tree_add(a: Params, b: Params) -> Params:
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def tree_scale(a: Params, s) -> Params:
    return jax.tree_util.tree_map(lambda x: x * s, a)


def tree_zeros_like(a: Params) -> Params:
    return jax.tree_util.tree_map(jnp.zeros_like, a)


def global_sq_dist(a: Params, b: Params) -> jax.Array:
    """``‖a − b‖²`` over all leaves (used by the FedProx proximal term)."""
    diffs = jax.tree_util.tree_map(
        lambda x, y: jnp.sum(jnp.square(x.astype(jnp.float32) - y.astype(jnp.float32))),
        a,
        b,
    )
    return jax.tree_util.tree_reduce(jnp.add, diffs, jnp.float32(0.0))


def trimmed_mean(stacked: Params, trim_ratio: float = 0.1) -> Params:
    """Byzantine-robust coordinate-wise trimmed mean over the client axis.

    Not in the reference (its only aggregator is the weighted mean) —
    provided as the robust-aggregation hook the FedAvg literature expects.
    """

    def one(leaf):
        c = leaf.shape[0]
        k = int(c * trim_ratio)
        srt = jnp.sort(leaf.astype(jnp.float32), axis=0)
        kept = srt[k : c - k] if c - 2 * k > 0 else srt
        return jnp.mean(kept, axis=0).astype(leaf.dtype)

    return jax.tree_util.tree_map(one, stacked)


def coordinate_median(stacked: Params) -> Params:
    """Coordinate-wise median over the client axis (robust aggregator)."""
    return jax.tree_util.tree_map(
        lambda l: jnp.median(l.astype(jnp.float32), axis=0).astype(l.dtype), stacked
    )


def parse_aggregator(spec: str):
    """``"mean" | "trimmed:<ratio>" | "median"`` -> tagged tuple.

    Shared by the simulation engine (FedSim) and the HTTP manager
    (Experiment): both select between :func:`weighted_tree_mean` and the
    robust order statistics above from the same spec strings."""
    if spec == "mean":
        return ("mean",)
    if spec == "median":
        return ("median",)
    if spec.startswith("trimmed:"):
        ratio = float(spec.split(":", 1)[1])
        if not (0.0 <= ratio < 0.5):
            raise ValueError(f"trim ratio must be in [0, 0.5), got {ratio}")
        return ("trimmed", ratio)
    raise ValueError(
        f"unknown aggregator {spec!r}; expected 'mean', 'median', "
        "or 'trimmed:<ratio>'"
    )


def apply_aggregator(spec, stacked: Params, weights: jax.Array) -> Params:
    """Dispatch a :func:`parse_aggregator` tuple over stacked client
    params — the single combine switch shared by the engine and the HTTP
    manager (robust rules ignore ``weights`` by design)."""
    if spec[0] == "trimmed":
        return trimmed_mean(stacked, spec[1])
    if spec[0] == "median":
        return coordinate_median(stacked)
    return weighted_tree_mean(stacked, weights)


def aggregate_stacked(
    spec, stacked: Params, n_samples: jax.Array, like: Params
) -> Params:
    """Combine ``[C, ...]``-stacked client params into one tree shaped/
    dtyped like ``like``, honoring a :func:`parse_aggregator` spec.

    The one shared round-combine tail (engine robust branch,
    StatefulClients, FedPer): for robust rules, zero-sample clients are
    excluded first — their "update" is the unchanged broadcast and
    enough of them would pull the order statistic to a no-op round; the
    weighted mean needs no exclusion (weight 0 contributes 0).
    """
    w = jnp.asarray(n_samples).astype(jnp.float32)
    if spec[0] != "mean":
        keep = np.flatnonzero(np.asarray(n_samples) > 0)
        if keep.size == 0:
            keep = np.arange(int(w.shape[0]))
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.take(a, jnp.asarray(keep), axis=0), stacked
        )
        merged = apply_aggregator(spec, stacked, None)
    else:
        merged = apply_aggregator(spec, stacked, w)
    return jax.tree_util.tree_map(
        lambda m, ref: jnp.asarray(m).astype(jnp.asarray(ref).dtype),
        merged, like,
    )


class StreamingMean:
    """O(model) streaming FedAvg — fold updates as they arrive.

    The buffered path stacks every cohort member's params before
    reducing (``O(C × model)`` host memory held until ``end_round``);
    this accumulator keeps only ``(Σ w_c · x_c, Σ w_c)`` and frees each
    update's tensors the moment they are folded, so manager memory is
    flat in cohort size.

    Numerics: accumulation is *sequential fp32 numpy* — deliberately not
    a tensordot — so the result is a deterministic function of arrival
    order and bit-matches the reference formula evaluated left-to-right
    in fp32 (the repo's unit-test oracle). It agrees with
    :func:`weighted_tree_mean` to fp32 reduction-order tolerance.

    Only valid for the ``"mean"`` aggregator: trimmed mean / coordinate
    median are order statistics over the full cohort and keep the
    buffered path (selected by spec in the HTTP manager).

    Thread-safety: ``add``/``mean`` take an internal lock. The ingest
    pipeline folds on an executor thread while the simulator path folds
    on the event loop, and numpy releases the GIL mid-ufunc — without
    the lock a concurrent first-fold could drop an update.
    """

    def __init__(self) -> None:
        self._sums: Optional[dict] = None
        self._weight = np.float32(0.0)
        self.count = 0
        self._lock = threading.Lock()

    def add(self, state_dict: dict, weight: float) -> None:
        """Fold one client's ``{name: array}`` update with sample weight
        ``weight``. After this returns the caller may drop the tensors."""
        w = np.float32(weight)
        with self._lock:
            if self._sums is None:
                self._sums = {
                    k: np.asarray(v, np.float32) * w
                    for k, v in state_dict.items()
                }
            else:
                for k, v in state_dict.items():
                    # in-place: no per-update O(model) allocation
                    self._sums[k] += np.asarray(v, np.float32) * w
            self._weight = self._weight + w
            self.count += 1

    @property
    def total_weight(self) -> float:
        return float(self._weight)

    def mean(self) -> Optional[dict]:
        """``Σ w·x / max(Σ w, 1e-9)`` as fp32 arrays, or None if nothing
        was folded. Matches :func:`weighted_tree_mean`'s clamped denom."""
        with self._lock:
            if self._sums is None:
                return None
            denom = np.maximum(self._weight, np.float32(1e-9))
            return {k: v / denom for k, v in self._sums.items()}


class ShardedStreamingMean:
    """N independent :class:`StreamingMean` partials — the manager's
    opt-in ``fold_shards>1`` ingest mode.

    Each shard folds on its own single-thread fold lane, so shards run
    concurrently while folds *within* a shard stay acceptance-ordered.
    The partials merge at ``mean()`` time: weighted sums are
    associative, so the merged result equals the sequential fold up to
    fp32 reduction order (pinned by the streaming≡buffered tolerance
    test in ``tests/test_ingest.py``). Same duck type as StreamingMean
    (``add``/``mean``/``count``/``total_weight``) with an extra
    ``shard=`` routing argument.
    """

    def __init__(self, shards: int = 1) -> None:
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.partials = [StreamingMean() for _ in range(int(shards))]

    @property
    def shards(self) -> int:
        return len(self.partials)

    @property
    def count(self) -> int:
        return sum(p.count for p in self.partials)

    @property
    def total_weight(self) -> float:
        return float(sum(p.total_weight for p in self.partials))

    def add(self, state_dict: dict, weight: float, shard: int = 0) -> None:
        self.partials[int(shard) % len(self.partials)].add(state_dict, weight)

    def mean(self) -> Optional[dict]:
        """Merge partial ``(Σ w·x, Σ w)`` pairs, then divide once."""
        sums: Optional[dict] = None
        weight = np.float32(0.0)
        for p in self.partials:
            with p._lock:
                if p._sums is None:
                    continue
                if sums is None:
                    sums = {
                        k: np.array(v, np.float32, copy=True)
                        for k, v in p._sums.items()
                    }
                else:
                    for k, v in p._sums.items():
                        sums[k] += v
                weight = weight + p._weight
        if sums is None:
            return None
        denom = np.maximum(weight, np.float32(1e-9))
        return {k: v / denom for k, v in sums.items()}


def psum_weighted_scalar_mean(
    values: jax.Array, weights: jax.Array, axis_name: str
) -> jax.Array:
    """:func:`weighted_scalar_mean` across a sharded client axis — the
    psum form used by the sharded FedPer/StatefulClients kernels (one
    definition of the loss-history weighting, meshless or sharded)."""
    w = weights.astype(jnp.float32)
    lsum = jax.lax.psum(
        jnp.tensordot(w, values.astype(jnp.float32), axes=(0, 0)), axis_name
    )
    wtot = jax.lax.psum(jnp.sum(w), axis_name)
    return lsum / jnp.maximum(wtot, 1e-9)


def tree_cast_like(tree: Params, like: Params) -> Params:
    """Cast every leaf to the dtype of the corresponding ``like`` leaf
    (the post-aggregation fp32 -> param-dtype step)."""
    return jax.tree_util.tree_map(
        lambda x, ref: jnp.asarray(x).astype(jnp.asarray(ref).dtype),
        tree, like,
    )
