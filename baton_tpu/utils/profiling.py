"""JAX profiler hooks (SURVEY §5 "Tracing/profiling: absent" — new).

Thin, always-importable wrappers around ``jax.profiler``:

* :func:`profile_trace` — context manager writing an XLA/TensorBoard
  trace (HLO timelines, per-op device time) to a directory. Enabled
  explicitly or via ``BATON_TPU_PROFILE=<dir>``; a no-op otherwise, so
  call sites can wrap hot paths unconditionally.
* :func:`annotate` — named region that shows up inside traces.
* :func:`timed` — wall-clock a function with ``block_until_ready`` on
  its outputs, so async XLA dispatch doesn't fake instant completion.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Optional, Tuple

import jax

ENV_VAR = "BATON_TPU_PROFILE"


@contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """Trace the enclosed block to ``log_dir`` (or ``$BATON_TPU_PROFILE``).

    No-op when neither is set — safe to leave in production paths.
    """
    log_dir = log_dir or os.environ.get(ENV_VAR)
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# Forensics arming: the alerting plane (obs/alerts.py) arms a one-shot
# profiler capture when a `capture: true` rule fires; the NEXT training
# step that reaches a `forensics_trace()` call site consumes the arm and
# traces itself into the armed directory. Consume-once under a lock so
# an alert storm cannot stack traces, and every jax.profiler failure is
# swallowed — forensics is advisory, it must never break the step.

import threading as _threading

_FORENSICS_LOCK = _threading.Lock()
_FORENSICS_DIR: Optional[str] = None


def arm_forensics_trace(log_dir: str) -> None:
    """Arm the next :func:`forensics_trace` call site to capture a
    ``jax.profiler`` trace into ``log_dir``. Re-arming before the
    previous arm is consumed just re-points the directory."""
    global _FORENSICS_DIR
    with _FORENSICS_LOCK:
        _FORENSICS_DIR = log_dir


def forensics_armed() -> bool:
    with _FORENSICS_LOCK:
        return _FORENSICS_DIR is not None


@contextmanager
def forensics_trace():
    """Consume a pending forensics arm around the enclosed block,
    yielding the trace directory (or None when unarmed / the profiler
    refused to start). Graceful no-op off-TPU and on profiler errors."""
    global _FORENSICS_DIR
    with _FORENSICS_LOCK:
        log_dir, _FORENSICS_DIR = _FORENSICS_DIR, None
    if not log_dir:
        yield None
        return
    trace = None
    try:
        os.makedirs(log_dir, exist_ok=True)
        trace = jax.profiler.trace(log_dir)
        trace.__enter__()
    except Exception:
        trace = None
    try:
        yield log_dir if trace is not None else None
    finally:
        if trace is not None:
            try:
                trace.__exit__(None, None, None)
            except Exception:
                pass


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``); nullcontext
    if the profiler lacks it (old jax)."""
    ta = getattr(jax.profiler, "TraceAnnotation", None)
    return ta(name) if ta is not None else nullcontext()


def timed(fn: Callable, *args: Any, **kwargs: Any) -> Tuple[Any, float]:
    """Run ``fn`` and return ``(result, seconds)``, blocking on all array
    outputs so the measurement covers device execution, not just
    dispatch."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0

def configure_jax_for_bench() -> None:
    """Shared benchmark-process JAX setup (bench.py / wave_sweep.py /
    tpu_suite.py / plan_probe.py): honor an explicit
    ``JAX_PLATFORMS=cpu`` request through ``jax.config`` (env-var
    overrides are unreliable against the axon plugin this container
    registers at interpreter startup), enable the persistent
    compilation cache so retries and probes reuse compiles, and apply
    the committed hardware attention sweep (when one exists) to the
    flash-vs-dense dispatcher — without this call the measured
    crossover artifact would be inert (r4 advisor finding)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR",
                       "/tmp/baton_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # repo root = …/baton_tpu/utils/profiling.py -> three dirnames up
    sweep = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "benchmarks", "attention_sweep_tpu.json")
    if os.path.exists(sweep):
        try:
            from baton_tpu.models.transformer import (
                configure_attention_dispatch)

            configure_attention_dispatch(sweep_path=sweep)
        except Exception:
            pass  # a malformed artifact must never kill a bench run


def resolve_artifact_path(out_path: str, run_has_tpu_success: bool,
                          prior_has_tpu_success) -> str:
    """Shared artifact-clobber policy for the hardware sweeps
    (wave_sweep.py, attention_sweep.py): never overwrite a recorded
    artifact holding TPU measurements with a run that produced none —
    a tunnel outage timing out every cell, or a CPU smoke run with
    plausible-looking numbers (both observed, r4). The lesser run is
    still evidence: it goes to a ``*_failed`` sibling instead.

    ``prior_has_tpu_success`` is a callable applied to the parsed prior
    JSON (artifact shapes differ per sweep); unreadable/foreign priors
    are treated as clobber-safe."""
    import json as _json

    if run_has_tpu_success:
        return out_path
    try:
        with open(out_path) as f:
            prior = _json.load(f)
        keep = bool(prior_has_tpu_success(prior))
    except (OSError, ValueError, TypeError, AttributeError, KeyError):
        return out_path
    if not keep:
        return out_path
    base, ext = os.path.splitext(out_path)
    return f"{base}_failed{ext or '.json'}"


def is_oom_error(e: Exception) -> bool:
    """True when an exception is XLA saying the program cannot fit in
    device memory. On real TPU backends an over-HBM program fails at
    COMPILE time with RESOURCE_EXHAUSTED and an allocation breakdown —
    that is a definitive "over budget", not an "analysis unavailable"
    (observed live on the tunneled v5e, round 4: the conv-shootout
    im2col wave kernel).

    A bare RESOURCE_EXHAUSTED is NOT enough: gRPC/transport reuse the
    same status for quota, rate-limit, and message-size failures, and
    classifying one of those as a device OOM turns a retryable flake
    into a definitive plan=inf skip (and makes bench.py refuse its one
    transient retry). Require corroborating memory/compile evidence —
    every genuine TPU OOM observed on this tunnel carried it ("memory
    space hbm", "Ran out of memory", an allocation breakdown, or the
    remote_compile helper path that only 500s on compile failures)."""
    msg = str(e).lower()
    if "out of memory" in msg or "allocation type: hlo temp" in msg:
        return True
    if "resource_exhausted" not in msg:
        return False
    return any(s in msg for s in (
        "hbm", "out of memory", "memory space", "allocation",
        "ran out of", "tpu compile", "remote_compile",
    ))


def plan_breakdown_gb(jitted, args) -> dict:
    """Components of XLA's static memory plan for ``jitted(*args)``,
    in GiB — the single byte-accounting rule every plan consumer
    shares (``total = arguments + outputs + temps - aliases``).
    Compiles (never executes); raises on compile failure — callers that
    need the OOM-vs-unavailable distinction use :func:`_plan_gb_of`."""
    ma = jitted.lower(*args).compile().memory_analysis()
    tot = (ma.argument_size_in_bytes + ma.output_size_in_bytes
           + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    return {
        "argument_gb": round(ma.argument_size_in_bytes / 2**30, 6),
        "output_gb": round(ma.output_size_in_bytes / 2**30, 6),
        "temp_gb": round(ma.temp_size_in_bytes / 2**30, 6),
        "alias_gb": round(ma.alias_size_in_bytes / 2**30, 6),
        "generated_code_gb": round(
            getattr(ma, "generated_code_size_in_bytes", 0) / 2**30, 6),
        "plan_gb": round(tot / 2**30, 6),
    }


def _plan_gb_of(jitted, args) -> Optional[float]:
    """XLA's static memory plan for ``jitted(*args)`` in GiB (total).
    Compiles (never executes).

    Returns ``float("inf")`` when the compile itself dies with
    RESOURCE_EXHAUSTED: the plan is then *known* to exceed HBM even
    though no byte count is available, and OOM-guard callers must treat
    it as over any finite budget rather than as missing analysis."""
    try:
        # 6 decimals (inside the breakdown): tiny test programs must not
        # round to a deceptive 0.0 GiB (real wave kernels are >= MBs)
        tot = plan_breakdown_gb(jitted, args)["plan_gb"]
        return tot if tot > 0 else None
    except Exception as e:
        return float("inf") if is_oom_error(e) else None


def _lower_wave_kernel(sim, params, data, n_samples, key,
                       wave_size: Optional[int] = None, n_epochs: int = 1):
    """(jitted, args) for ONE wave of ``sim``'s round, honoring a
    trainable/frozen partition — the program whose memory plan stands in
    for the round's footprint. A ``wave_size`` larger than the cohort is
    PADDED to size (run_round pads its last wave the same way) — slicing
    alone would hand vmap mismatched leading axes, and the resulting
    trace error must not read as "no analysis, assume it fits"."""
    import jax
    import jax.numpy as jnp

    tr, fz = sim._split(params)
    n_samples = jnp.asarray(n_samples)
    c = int(n_samples.shape[0])
    w = wave_size or c
    take = min(w, c)
    d0 = jax.tree_util.tree_map(lambda a: a[:take], data)
    n0 = n_samples[:take]
    r0 = jax.random.split(key, take)
    if take < w:
        d0, n0, r0 = sim._pad_wave(d0, n0, r0, w)
    jitted = jax.jit(lambda a, b, d, n, r: sim._wave_sums_raw(
        a, b, d, n, r, n_epochs))
    return jitted, (tr, fz, d0, n0, r0)


def peak_hbm_gb(device, jitted=None, args: Optional[Tuple] = None
                ) -> Tuple[Optional[float], Optional[str]]:
    """Best-available peak-HBM estimate for a single-program workload.

    Prefers the runtime allocator's ``peak_bytes_in_use``; when the
    runtime surfaces no allocator stats (the tunneled axon TPU reports
    none — observed every round-3 run), falls back to XLA's static
    memory plan for ``jitted(*args)``. Returns ``(GiB, source)`` with
    source ``"allocator"`` / ``"xla_memory_analysis"``, or
    ``(None, None)``. The fallback COMPILES ``jitted`` if it isn't
    already cached — callers on a wall-clock budget must gate on it.
    """
    try:
        stats = device.memory_stats() or {}
        peak = stats.get("peak_bytes_in_use", 0)
        if peak:
            return round(peak / 2**30, 6), "allocator"
    except Exception:
        pass
    if jitted is not None and args is not None:
        gb = _plan_gb_of(jitted, args)
        if gb is not None and gb != float("inf"):
            return gb, "xla_memory_analysis"
    return None, None


# Plan-space budgets for the OOM guard, in two tiers.
#
# Default tier: HBM capacity minus runtime/framework headroom — correct
# for kernels whose XLA memory plan tracks the true allocation
# (matmul-shaped programs: im2col convs, transformers).
#
# Anchored tier (ANCHORED_DIRECT_CONV_BUDGET_GB): for the direct-conv
# ResNet wave kernels the plan systematically OVERCOUNTS the executed
# peak (conv tile-padding accounting). Hardware anchors on the v5e
# (16 GB): the round-3 sweep EXECUTED the wave-64 kernel — whose plan
# measures 17.42 GiB — at 0.942 rounds/s, while the full-cohort
# wave-128 kernel (plan ~22 GiB by per-client slope) OOM'd and took
# the tunnel down for hours. Anchor provenance verified before raising
# the threshold: `git diff r3..HEAD` over models/resnet.py (direct
# path: pure rename), parallel/engine.py, core/training.py,
# ops/{aggregation,padding}.py is empty — today's direct wave kernel
# is HLO-identical to the one r3 executed, and the kernel sees only
# wave-sized avals so cohort size cannot change its plan.
HBM_BUDGET_GB = {
    "TPU v4": 29.0,       # 32 GB
    "TPU v5 lite": 13.5,  # v5e, 16 GB
    "TPU v5e": 13.5,
    "TPU v5": 90.0,       # v5p, 95 GB
    "TPU v5p": 90.0,
    "TPU v6 lite": 28.0,  # v6e, 32 GB
    "TPU v6e": 28.0,
}
# unknown device: the conservative v5e value
DEFAULT_HBM_BUDGET_GB = 13.5

# The anchored overlay applies ONLY to the direct-conv ResNet wave
# kernel class, where the plan provably overcounts (conv tile-padding):
# the r3-executed wave-64 kernel plans at 17.42 GiB on a 16 GB chip.
# It must NOT be used for matmul-shaped kernels (im2col, transformers)
# whose plans track real allocation — the r4 im2col headline's plan of
# 19.2 GiB was a REAL over-capacity demand (compile RESOURCE_EXHAUSTED).
ANCHORED_DIRECT_CONV_BUDGET_GB = {
    "TPU v5 lite": 17.5,  # anchored: plan 17.42 ran, ~22 OOM'd
    "TPU v5e": 17.5,
}

# The exact kernel identity the r3 hardware anchor covers: the direct-
# lowering ResNet wave kernel at per-client batch 32 (wave_sweep_tpu.json
# b32/spc48 wave-64, plan 17.42 GiB, EXECUTED at 0.942 rounds/s). The
# plan-overcount evidence extends no further — a direct_b48 kernel is a
# different program whose 16-17.5 GiB plan could be a real over-HBM
# demand, and executing one is the multi-hour-outage scenario.
ANCHORED_CONV_KERNEL = {"impl": "direct", "batch_size": 32}


def conv_kernel_class(impl: str, batch_size: int = 32) -> str:
    """OOM-guard kernel class for a per-client-conv wave kernel.

    Returns ``"anchored_direct_conv"`` only for the FULL anchored
    kernel identity (lowering impl AND per-client batch size matching
    :data:`ANCHORED_CONV_KERNEL`); every other conv config — im2col,
    shift, or an unanchored direct batch — gets the conservative
    ``"default"`` tier."""
    if (impl == ANCHORED_CONV_KERNEL["impl"]
            and int(batch_size) == ANCHORED_CONV_KERNEL["batch_size"]):
        return "anchored_direct_conv"
    return "default"


def hbm_budget_gb(device, kernel_class: str = "default") -> float:
    """Plan-space OOM-guard budget for ``device``.

    ``kernel_class="anchored_direct_conv"`` selects the calibrated
    overlay for the direct-conv ResNet wave kernels (see
    ANCHORED_DIRECT_CONV_BUDGET_GB); every other kernel class gets the
    conservative capacity-minus-headroom budget, because for
    matmul-shaped programs the plan is close to the true allocation and
    admitting plans above physical HBM would execute a real OOM."""
    kind = getattr(device, "device_kind", "")
    if kernel_class == "anchored_direct_conv":
        for prefix, budget in ANCHORED_DIRECT_CONV_BUDGET_GB.items():
            if kind.startswith(prefix):
                return budget
    for prefix, budget in HBM_BUDGET_GB.items():
        if kind.startswith(prefix):
            return budget
    return DEFAULT_HBM_BUDGET_GB


def fedsim_wave_plan_gb(sim, params, data, n_samples, key,
                        wave_size: Optional[int] = None,
                        n_epochs: int = 1) -> Optional[float]:
    """XLA's static HBM plan (GiB) for one wave's kernel, compiled
    WITHOUT executing. The OOM guard: an out-of-memory execution on the
    tunneled chip causes a multi-hour outage (r3 postmortem), so
    benchmark stages check the compiler's own budget first and skip —
    recording the plan — instead of running a program that cannot fit.
    Returns None when analysis is unavailable (CPU/smoke — proceed) and
    ``float("inf")`` when the compile itself RESOURCE_EXHAUSTs (a
    definitive does-not-fit — guards must skip)."""
    try:
        jitted, args = _lower_wave_kernel(sim, params, data, n_samples,
                                          key, wave_size, n_epochs)
        return _plan_gb_of(jitted, args)
    except Exception as e:
        return float("inf") if is_oom_error(e) else None


def fedsim_wave_hbm(device, sim, params, data, n_samples, key,
                    wave_size: Optional[int] = None, n_epochs: int = 1,
                    remaining_s: Optional[float] = None,
                    ) -> Tuple[Optional[float], Optional[str]]:
    """Peak-HBM estimate for one wave of a :class:`FedSim` round.

    Allocator stats when available (cheap); otherwise XLA's static plan
    for one wave's kernel. Lowering compiles a fresh program, so when
    ``remaining_s`` is given the fallback is skipped below a 60 s floor
    — a slow tunnel compile must never turn an already-measured
    benchmark into a timeout. Single shared implementation for
    bench.py / wave_sweep.py / tpu_suite.py.
    """
    gb, src = peak_hbm_gb(device)
    if gb is not None:
        return gb, src
    if remaining_s is not None and remaining_s < 60.0:
        return None, None
    try:
        jitted, args = _lower_wave_kernel(sim, params, data, n_samples,
                                          key, wave_size, n_epochs)
        return peak_hbm_gb(device, jitted, args)
    except Exception:
        return None, None


def fedsim_fused_donation_plan(sim, params, data, n_samples, key,
                               n_rounds: int = 2, n_epochs: int = 1,
                               wave_size: Optional[int] = None) -> dict:
    """XLA static memory plans for the fused multi-round program
    compiled WITH and WITHOUT buffer donation — the measured answer to
    "what does ``donate_argnums`` on the round step actually buy".

    Compiles both variants (never executes); donation shows up in the
    plan's ``alias_gb`` (the donated params/server-opt inputs alias the
    outputs, so the globals stop being double-buffered across the
    dispatch). Returns ``{"donate_on": breakdown, "donate_off":
    breakdown, "delta_gb": off - on}`` with :func:`plan_breakdown_gb`
    dicts; raises on compile failure — callers decide whether an
    unmeasured delta is skippable (and must record why).
    """
    import jax
    import jax.numpy as jnp

    from baton_tpu.ops.padding import round_up

    tr, fz = sim._split(params)
    n_samples = jnp.asarray(n_samples)
    c = int(n_samples.shape[0])
    unit = sim._clients_per_wave_unit()
    wave = round_up(wave_size if wave_size is not None else c, unit)
    n_waves = -(-c // wave)
    rngs = jax.random.split(key, c)
    data, n_samples, _ = sim._pad_wave(data, n_samples, rngs,
                                       n_waves * wave)
    data_w = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a).reshape((n_waves, wave) + a.shape[1:]),
        data,
    )
    n_w = n_samples.reshape(n_waves, wave)
    sos = (sim.server_optimizer.init(tr)
           if sim.server_optimizer is not None else None)
    args = (tr, fz, data_w, n_w, key, sos)
    out = {}
    for label, donate in (("donate_on", True), ("donate_off", False)):
        fn = sim._make_rounds_fused(n_epochs, n_rounds, donate=donate)
        out[label] = plan_breakdown_gb(fn, args)
    out["delta_gb"] = round(
        out["donate_off"]["plan_gb"] - out["donate_on"]["plan_gb"], 6
    )
    return out
