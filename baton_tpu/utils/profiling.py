"""JAX profiler hooks (SURVEY §5 "Tracing/profiling: absent" — new).

Thin, always-importable wrappers around ``jax.profiler``:

* :func:`profile_trace` — context manager writing an XLA/TensorBoard
  trace (HLO timelines, per-op device time) to a directory. Enabled
  explicitly or via ``BATON_TPU_PROFILE=<dir>``; a no-op otherwise, so
  call sites can wrap hot paths unconditionally.
* :func:`annotate` — named region that shows up inside traces.
* :func:`timed` — wall-clock a function with ``block_until_ready`` on
  its outputs, so async XLA dispatch doesn't fake instant completion.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Optional, Tuple

import jax

ENV_VAR = "BATON_TPU_PROFILE"


@contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """Trace the enclosed block to ``log_dir`` (or ``$BATON_TPU_PROFILE``).

    No-op when neither is set — safe to leave in production paths.
    """
    log_dir = log_dir or os.environ.get(ENV_VAR)
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``); nullcontext
    if the profiler lacks it (old jax)."""
    ta = getattr(jax.profiler, "TraceAnnotation", None)
    return ta(name) if ta is not None else nullcontext()


def timed(fn: Callable, *args: Any, **kwargs: Any) -> Tuple[Any, float]:
    """Run ``fn`` and return ``(result, seconds)``, blocking on all array
    outputs so the measurement covers device execution, not just
    dispatch."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0
