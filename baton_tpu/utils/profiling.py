"""JAX profiler hooks (SURVEY §5 "Tracing/profiling: absent" — new).

Thin, always-importable wrappers around ``jax.profiler``:

* :func:`profile_trace` — context manager writing an XLA/TensorBoard
  trace (HLO timelines, per-op device time) to a directory. Enabled
  explicitly or via ``BATON_TPU_PROFILE=<dir>``; a no-op otherwise, so
  call sites can wrap hot paths unconditionally.
* :func:`annotate` — named region that shows up inside traces.
* :func:`timed` — wall-clock a function with ``block_until_ready`` on
  its outputs, so async XLA dispatch doesn't fake instant completion.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Optional, Tuple

import jax

ENV_VAR = "BATON_TPU_PROFILE"


@contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """Trace the enclosed block to ``log_dir`` (or ``$BATON_TPU_PROFILE``).

    No-op when neither is set — safe to leave in production paths.
    """
    log_dir = log_dir or os.environ.get(ENV_VAR)
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``); nullcontext
    if the profiler lacks it (old jax)."""
    ta = getattr(jax.profiler, "TraceAnnotation", None)
    return ta(name) if ta is not None else nullcontext()


def timed(fn: Callable, *args: Any, **kwargs: Any) -> Tuple[Any, float]:
    """Run ``fn`` and return ``(result, seconds)``, blocking on all array
    outputs so the measurement covers device execution, not just
    dispatch."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def peak_hbm_gb(device, jitted=None, args: Optional[Tuple] = None
                ) -> Tuple[Optional[float], Optional[str]]:
    """Best-available peak-HBM estimate for a single-program workload.

    Prefers the runtime allocator's ``peak_bytes_in_use``; when the
    runtime surfaces no allocator stats (the tunneled axon TPU reports
    none — observed every round-3 run), falls back to XLA's static
    memory plan for ``jitted(*args)``: arguments + outputs + temps minus
    aliased buffers — the compiler's own HBM budget for the program, a
    lower bound on (and in practice ~equal to) the allocator peak.
    Returns ``(GiB, source)`` with source ``"allocator"`` /
    ``"xla_memory_analysis"``, or ``(None, None)`` when neither is
    available. Note the fallback COMPILES ``jitted`` if it isn't
    already cached — callers on a wall-clock budget must gate on it.
    """
    try:
        stats = device.memory_stats() or {}
        peak = stats.get("peak_bytes_in_use", 0)
        if peak:
            # 6 decimals on both branches: a sub-MB peak must not round
            # to a deceptive 0.0 GiB
            return round(peak / 2**30, 6), "allocator"
    except Exception:
        pass
    if jitted is not None and args is not None:
        try:
            ma = jitted.lower(*args).compile().memory_analysis()
            tot = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                   + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
            if tot > 0:
                # 6 decimals: tiny test programs must not round to a
                # deceptive 0.0 GiB (real wave kernels are >= MBs)
                return round(tot / 2**30, 6), "xla_memory_analysis"
        except Exception:
            pass
    return None, None


def fedsim_wave_hbm(device, sim, params, data, n_samples, key,
                    wave_size: Optional[int] = None, n_epochs: int = 1,
                    remaining_s: Optional[float] = None,
                    ) -> Tuple[Optional[float], Optional[str]]:
    """Peak-HBM estimate for one wave of a :class:`FedSim` round.

    Allocator stats when available (cheap); otherwise lowers ONE wave's
    kernel (``_wave_sums_raw`` with no frozen partition — callers with a
    LoRA split need their own program) for XLA's static plan. Lowering
    compiles a fresh program, so when ``remaining_s`` is given the
    fallback is skipped below a 60 s floor — a slow tunnel compile must
    never turn an already-measured benchmark into a timeout. This is the
    single shared implementation for bench.py / wave_sweep.py /
    r4_tpu_suite.py (it was once four copies).
    """
    import jax
    import jax.numpy as jnp

    gb, src = peak_hbm_gb(device)
    if gb is not None:
        return gb, src
    if remaining_s is not None and remaining_s < 60.0:
        return None, None
    try:
        n_samples = jnp.asarray(n_samples)
        if wave_size is None:
            wave_size = int(n_samples.shape[0])
        d0 = jax.tree_util.tree_map(lambda a: a[:wave_size], data)
        n0 = n_samples[:wave_size]
        r0 = jax.random.split(key, wave_size)
        jitted = jax.jit(lambda pr, d, n, r: sim._wave_sums_raw(
            pr, None, d, n, r, n_epochs))
        return peak_hbm_gb(device, jitted, (params, d0, n0, r0))
    except Exception:
        return None, None
