"""JAX profiler hooks (SURVEY §5 "Tracing/profiling: absent" — new).

Thin, always-importable wrappers around ``jax.profiler``:

* :func:`profile_trace` — context manager writing an XLA/TensorBoard
  trace (HLO timelines, per-op device time) to a directory. Enabled
  explicitly or via ``BATON_TPU_PROFILE=<dir>``; a no-op otherwise, so
  call sites can wrap hot paths unconditionally.
* :func:`annotate` — named region that shows up inside traces.
* :func:`timed` — wall-clock a function with ``block_until_ready`` on
  its outputs, so async XLA dispatch doesn't fake instant completion.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Optional, Tuple

import jax

ENV_VAR = "BATON_TPU_PROFILE"


@contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """Trace the enclosed block to ``log_dir`` (or ``$BATON_TPU_PROFILE``).

    No-op when neither is set — safe to leave in production paths.
    """
    log_dir = log_dir or os.environ.get(ENV_VAR)
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``); nullcontext
    if the profiler lacks it (old jax)."""
    ta = getattr(jax.profiler, "TraceAnnotation", None)
    return ta(name) if ta is not None else nullcontext()


def timed(fn: Callable, *args: Any, **kwargs: Any) -> Tuple[Any, float]:
    """Run ``fn`` and return ``(result, seconds)``, blocking on all array
    outputs so the measurement covers device execution, not just
    dispatch."""
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def peak_hbm_gb(device, jitted=None, args: Optional[Tuple] = None
                ) -> Tuple[Optional[float], Optional[str]]:
    """Best-available peak-HBM estimate for a single-program workload.

    Prefers the runtime allocator's ``peak_bytes_in_use``; when the
    runtime surfaces no allocator stats (the tunneled axon TPU reports
    none — observed every round-3 run), falls back to XLA's static
    memory plan for ``jitted(*args)``: arguments + outputs + temps minus
    aliased buffers — the compiler's own HBM budget for the program, a
    lower bound on (and in practice ~equal to) the allocator peak.
    Returns ``(GiB, source)`` with source ``"allocator"`` /
    ``"xla_memory_analysis"``, or ``(None, None)`` when neither is
    available. Note the fallback COMPILES ``jitted`` if it isn't
    already cached — callers on a wall-clock budget must gate on it.
    """
    try:
        stats = device.memory_stats() or {}
        peak = stats.get("peak_bytes_in_use", 0)
        if peak:
            return round(peak / 2**30, 3), "allocator"
    except Exception:
        pass
    if jitted is not None and args is not None:
        try:
            ma = jitted.lower(*args).compile().memory_analysis()
            tot = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                   + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
            if tot > 0:
                return round(tot / 2**30, 3), "xla_memory_analysis"
        except Exception:
            pass
    return None, None
