"""Auxiliary subsystems (SURVEY §5 / §7 step 7).

The reference has none of these — no checkpointing (a manager restart
loses the global model, SURVEY §5 "Checkpoint/resume: absent"), no
metrics beyond prints, no profiler, no fault injection. They are new
capabilities, flagged as such in SURVEY, built TPU-first:

* :mod:`baton_tpu.utils.checkpoint` — orbax round-granular save/resume.
* :mod:`baton_tpu.utils.metrics` — counters/gauges/timers + JSON export.
* :mod:`baton_tpu.utils.profiling` — JAX profiler traces + device timing.
* :mod:`baton_tpu.utils.faults` — HTTP-layer fault injection for
  elasticity tests.
"""

from baton_tpu.utils.checkpoint import Checkpointer, RestoredState
from baton_tpu.utils.metrics import Metrics
from baton_tpu.utils.profiling import annotate, profile_trace, timed

__all__ = [
    "Checkpointer",
    "RestoredState",
    "Metrics",
    "annotate",
    "profile_trace",
    "timed",
]
