"""Metrics registry — counters, gauges, histogram timers, JSON export.

The reference's only observability is ``print`` statements and a broken
``loss_history`` endpoint (SURVEY §5 "Metrics/logging"). This registry
backs the manager's ``GET /{name}/metrics`` endpoint and the engine's
per-round/per-wave timings. Pure Python, no deps, threadsafe enough for
the asyncio + ``to_thread`` training model (GIL-atomic dict ops plus a
lock around multi-field histogram updates).

Timers are fixed-bucket log-spaced histograms: every ``observe`` lands
in one of ``len(_BUCKET_BOUNDS)+1`` buckets, so the snapshot can report
p50/p95/p99 with bounded error (one bucket's width, ratio √2) at O(1)
memory per timer — the SLO quantiles the scenario harness keys on.
"""

from __future__ import annotations

import asyncio
import bisect
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Declared metric registries.
#
# Dashboards and alert rules key on exact metric names, so every
# counter/timer/gauge touched under baton_tpu/server/ or
# baton_tpu/loadgen/ must be declared
# here — batonlint rule BTL030 enforces it (the linter parses these
# literals with ast.literal_eval; keep them plain literals, no computed
# values). Counter FAMILIES whose suffix is built at runtime (f-strings
# keyed on an HTTP status, for example) declare their static prefix in
# DECLARED_COUNTER_PREFIXES instead.
DECLARED_COUNTERS = frozenset({
    # manager: recovery / lifecycle
    "recovery_rounds_aborted",
    "recovery_rounds_resumed",
    "clients_culled",
    "rounds_finished",
    "broadcast_timeout",
    # manager: downlink data plane
    "range_resumes",
    "bytes_broadcast",
    "blob_hits_delta",
    "blob_hits_full",
    # manager: uplink ingest / admission control
    "ingest_rejected_429",
    "uploads_rejected_413",
    "control_rejected_413",
    "bytes_uploaded",
    "duplicate_updates_deduped",
    "repeat_updates_ignored",
    "updates_received",
    "compressed_updates_received",
    "chunk_bytes_received",
    "chunked_uploads_assembled",
    # manager: secure aggregation
    "secure_rounds_aborted_keys",
    "secure_rounds_aborted_shares",
    "secure_rounds_unrecoverable",
    "secure_dropouts_recovered",
    # manager: tracing
    "trace_spans_ingested",
    "trace_spans_rejected",
    # worker: secure aggregation downgrade guard
    "updates_refused_secure_downgrade",
    # worker: outbox / delivery
    "outbox_reloaded_from_disk",
    "updates_delivered",
    "update_retries",
    "update_backpressure_429",
    # worker: downlink blob fetch
    "blob_reused_anchor",
    "blob_fetch_delta",
    "blob_fetch_delta_chain",
    "blob_delta_digest_mismatch",
    "blob_fetch_failed",
    "blob_fetch_full",
    "blob_range_resumes",
    # worker: uplink chunked upload
    "chunk_upload_resumes",
    "chunk_bytes_resume_skipped",
    "chunk_bytes_put",
    # worker: control plane
    "broadcast_rejected_413",
    "train_epochs_completed",
    # worker: trace shipping
    "trace_spans_shipped",
    "trace_ship_failed",
    # manager: hierarchical aggregation (edge partial merge)
    "updates_received_edge_partial",
    "edge_contributors_credited",
    "edge_contributor_conflicts",
    "edge_contributors_unknown",
    "updates_refused_edge_secure",
    "updates_refused_edge_unsupported",
    # edge aggregator (server/edge.py)
    "edge_registers_proxied",
    "edge_heartbeats_proxied",
    "edge_relay_notifies",
    "edge_relay_failed",
    "edge_blob_hits",
    "edge_blob_fetches",
    "edge_blob_fetch_failed",
    "edge_bytes_served",
    "edge_bytes_fetched",
    "edge_range_resumes",
    "edge_updates_folded",
    "edge_updates_proxied",
    "edge_updates_refused_secure",
    "edge_partials_shipped",
    "edge_partial_ship_failed",
    "edge_partial_refused",
    "edge_partials_abandoned",
    # worker: edge routing fallback
    "edge_route_fallbacks",
    # loadgen: open-loop scenario driver (baton_tpu/loadgen/engine.py)
    "scenario_rounds_started",
    "scenario_rounds_refused_423",
    "scenario_start_round_errors",
    "scenario_rounds_forced_end",
    "scenario_workers_joined",
    "scenario_workers_left",
    "scenario_warmup_rounds",
    "scenario_edges_started",
    "scenario_edges_killed",
    # fleet health plane (server/fleet.py ledger)
    "fleet_observations",
    "history_snapshots",
    # compute plane (baton_tpu/obs/compute.py probe records)
    "compute_recompiles",        # worker: jit cache misses after the first
    "compute_records_invalid",   # manager: records dropped by sanitizer
    # manager: edge-tier phase wall times folded into round counter
    # deltas (float seconds; shipped per round in the partial's meta)
    "edge_phase_fold_s",
    "edge_phase_blob_fetch_s",
    "edge_phase_settle_s",
    "edge_phase_ship_prev_s",
    # alerting plane (baton_tpu/obs/alerts.py engine, per node)
    "alerts_fired_total",
    "alerts_resolved_total",
    "alerts_eval_errors",
    "alerts_captures_armed",
    "alerts_captures_built",   # manager: forensics bundles materialized
    # runbook/actuation plane (baton_tpu/obs/runbooks.py engine)
    "runbooks_entered_total",    # rule transitions into ACTIVE
    "runbooks_exited_total",     # hysteresis exits back to idle
    "runbooks_eval_errors",      # advisory: evaluation/actuation failures
    "runbooks_actuations_total",  # remediations actually applied to rounds
    # retention (trace-spool GC + jsonl rotation PeriodicTasks)
    "trace_spool_gc_removed",
    "jsonl_rotations",
    # replication control plane (server/replication.py + ha wiring)
    "wal_segments_shipped",
    "wal_bytes_shipped",
    "wal_segments_applied",
    "wal_segments_refused_stale",
    "wal_resyncs",
    "wal_snapshot_catchups",
    "wal_snapshot_catchups_sent",
    "wal_ship_errors",
    "wal_ship_fenced",
    "ha_promotions",
    "ha_lease_renewals",
    "heartbeats_redirected",
    # manager: journaled-payload recovery (resume without re-training)
    "recovery_updates_reused",
    "recovery_payload_replays_failed",
    "recovery_rebroadcasts",
    "journal_payloads_journaled",
    "journal_payloads_skipped_large",
    "chunk_sessions_restored",
    # worker: root-ring failover + topology redirects
    "root_failovers",
    "root_redirects_followed",
    # loadgen: root-kill chaos phases
    "scenario_roots_killed",
})

DECLARED_COUNTER_PREFIXES = (
    "updates_abandoned_",   # worker: f"updates_abandoned_{status}"
    "broadcast_rejected_",  # manager: f"broadcast_rejected_{status}"
)

# Timers/histograms observed under baton_tpu/server/ (BTL030 audits
# .observe()/.timer() names against this set).
DECLARED_TIMERS = frozenset({
    "round_s",          # manager: reporting-window duration per round
    "checkpoint_s",     # manager: orbax save latency
    "notify_s",         # manager: per-client round_start broadcast POST
    "ingest_decode_s",  # manager: off-loop upload decode+validate
    "ingest_fold_s",    # manager: per-shard streaming fold
    "heartbeat_s",      # worker: heartbeat GET round-trip
    "loop_lag_s",       # both: event-loop scheduling delay (LoopLagProbe)
    # edge aggregator (server/edge.py)
    "edge_blob_fetch_s",    # edge: root blob fetch on cohort cache miss
    "edge_partial_ship_s",  # edge: partial upload to root, end to end
    "edge_relay_s",         # edge: root→worker notify/secure relay hop
    # fleet health plane
    "local_train_s",    # worker: self-measured local training wall time
    "upload_s",         # worker: one update POST end to end
    # compute plane (baton_tpu/obs/compute.py probe)
    "compute_compile_s",  # worker/engine: jit compile wall per round
})

# Timers whose histogram must carry a trace exemplar: every direct
# ``observe()`` on these names is required (batonlint BTL032) to pass
# the active span context via ``exemplar=``, so a p99 spike on
# ``/metrics`` always links to a fetchable round trace. Plain literal —
# the linter parses this with ast.literal_eval like the sets above.
DECLARED_EXEMPLAR_TIMERS = frozenset({
    "round_s",
    "local_train_s",
    "upload_s",
    "compute_compile_s",
})

# Gauges set under baton_tpu/server/ (BTL030 audits .set_gauge() names).
DECLARED_GAUGES = frozenset({
    # manager
    "chunk_sessions_active",
    "sim_wave",
    "sim_waves_total",
    "ingest_queue_depth",
    "clients_registered",
    "rounds_completed",
    "round_in_progress",
    "dh_cache_size",
    "dh_cache_hits",
    "dh_cache_misses",
    # worker
    "outbox_pending",
    "train_epoch",
    "train_epoch_loss",
    # edge aggregator (server/edge.py)
    "edge_cohort_size",
    "edge_round_pending",
    "edge_cache_bytes",
    # both: LoopLagProbe scheduling-delay gauge
    "loop_lag_s",
    # loadgen: scenario driver state
    "scenario_workers_available",
    "scenario_workers_alive",
    "scenario_phase_index",
    "scenario_availability",
    # fleet health plane: advisory per-class client counts
    # (server/fleet.py classifications exported by the manager/edges)
    "fleet_clients_total",
    "fleet_clients_healthy",
    "fleet_clients_slow",
    "fleet_clients_flaky",
    "fleet_clients_degrading",
    "fleet_clients_inactive",
    # alerting plane: current rule-state counts (obs/alerts.py engine)
    "alerts_firing",
    "alerts_pending",
    # runbook plane: rules currently ACTIVE (obs/runbooks.py engine)
    "runbooks_active",
    # compute plane (baton_tpu/obs/compute.py probe records; latest round)
    "compute_mfu",
    "compute_samples_per_sec_per_chip",
    "compute_peak_hbm_gb",
    "compute_recompile_storm",
    "compute_steps",
    "compute_reporters",
    # replication control plane (role, lease, WAL positions)
    "replication_epoch",
    "replication_role_active",
    "replication_standbys",
    "replication_wal_shipped_offset",
    "replication_wal_applied_offset",
    "replication_wal_lag_s",
    "replication_lease_remaining_s",
})


# Log-spaced bucket upper bounds (seconds), ratio √2, 100 µs … ~1 677 s.
# 48 buckets + one overflow keep every histogram at a fixed 49 ints.
_BUCKET_RATIO = 2.0 ** 0.5
_BUCKET_BOUNDS = tuple(1e-4 * _BUCKET_RATIO ** i for i in range(48))

# How long a timer holds on to its worst-observation exemplar before a
# smaller observation may replace it: long enough to survive a scrape
# interval, short enough that a stale p99 trace link ages out.
_EXEMPLAR_TTL_S = 60.0


class _TimerStat:
    """One timer's fixed-bucket histogram plus the legacy scalar stats."""

    __slots__ = ("count", "total", "min", "max", "last", "buckets",
                 "exemplar")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0
        self.buckets: List[int] = [0] * (len(_BUCKET_BOUNDS) + 1)
        # worst recent observation's span context: {"seconds", "trace_id",
        # "span_id", "ts"} — the /metrics link from a p99 spike to the
        # round trace that produced it
        self.exemplar: Optional[dict] = None

    def observe(
        self,
        seconds: float,
        exemplar: Optional[Tuple[str, str]] = None,
    ) -> None:
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)
        self.last = seconds
        self.buckets[bisect.bisect_left(_BUCKET_BOUNDS, seconds)] += 1
        if exemplar is not None:
            ex = self.exemplar
            now = time.time()
            if (
                ex is None
                or seconds >= ex["seconds"]
                or now - ex["ts"] > _EXEMPLAR_TTL_S
            ):
                self.exemplar = {
                    "seconds": seconds,
                    "trace_id": exemplar[0],
                    "span_id": exemplar[1],
                    "ts": now,
                }

    def quantile(self, q: float) -> float:
        """Histogram quantile with linear interpolation inside the
        landing bucket, clamped to the observed [min, max] — error is
        bounded by one bucket's width (ratio √2)."""
        if not self.count:
            return 0.0
        rank = q * (self.count - 1)
        seen = 0
        for i, n in enumerate(self.buckets):
            if not n:
                continue
            if rank < seen + n:
                lo = _BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (
                    _BUCKET_BOUNDS[i]
                    if i < len(_BUCKET_BOUNDS)
                    else max(self.max, lo)
                )
                frac = (rank - seen + 1.0) / n
                est = lo + (hi - lo) * min(1.0, frac)
                return min(max(est, self.min), self.max)
            seen += n
        return self.max

    def to_json(self) -> dict:
        out = {
            "count": self.count,
            "total_s": self.total,
            "mean_s": self.total / self.count if self.count else 0.0,
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
            "last_s": self.last,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }
        if self.exemplar is not None:
            out["exemplar"] = dict(self.exemplar)
        return out


class Metrics:
    def __init__(self, history_limit: int = 240) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, _TimerStat] = {}
        self._history: deque = deque(maxlen=max(2, history_limit))
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(
        self,
        name: str,
        seconds: float,
        exemplar: Optional[Tuple[str, str]] = None,
    ) -> None:
        """Record one histogram observation. ``exemplar`` is the active
        ``(trace_id, span_id)`` pair (``tracing.current_context()``) —
        required on timers in :data:`DECLARED_EXEMPLAR_TIMERS` so the
        worst recent observation links back to its round trace."""
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = _TimerStat()
            stat.observe(seconds, exemplar=exemplar)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            # auto-capture the span context active at exit: timers used
            # under a `with tracer.span(...)` get exemplars for free
            from baton_tpu.utils import tracing
            self.observe(name, time.perf_counter() - t0,
                         exemplar=tracing.current_context())

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: v.to_json() for k, v in self._timers.items()},
            }

    # ------------------------------------------------------------------
    def record_history(
        self,
        ts: Optional[float] = None,
        snapshot: Optional[dict] = None,
    ) -> dict:
        """Append a timestamped snapshot to the bounded history ring
        (``GET /{name}/metrics/history``) so scrapers and the SLO
        evaluator can compute rates and windowed deltas without
        maintaining their own state. ``snapshot`` lets the caller record
        a DERIVED snapshot (extra computed gauges) instead of the raw
        registry. Returns the recorded entry."""
        snap = dict(snapshot) if snapshot is not None else self.snapshot()
        snap["ts"] = round(time.time() if ts is None else ts, 6)
        with self._lock:
            self._history.append(snap)
            n = len(self._history)
        self.inc("history_snapshots")
        return dict(snap, samples=n)

    def history(self, since: Optional[float] = None) -> List[dict]:
        """The recorded snapshot ring, oldest first. ``since`` keeps
        only samples with ``ts`` strictly after it, so pollers (the ops
        console) can fetch deltas instead of the full ring."""
        with self._lock:
            samples = list(self._history)
        if since is None:
            return samples
        return [s for s in samples if s.get("ts", 0.0) > since]


class LoopLagProbe:
    """Event-loop scheduling-delay probe — the runtime complement to
    batonlint BTL001. Arms ``call_later(interval)`` and measures how
    late the callback actually fires: any synchronous work hogging the
    loop (a blocking read, an un-thread-ed decode) shows up directly as
    lag. Publishes both a gauge (latest lag) and a histogram (p95/p99
    over the run) under ``loop_lag_s``."""

    def __init__(
        self,
        metrics: Metrics,
        interval: float = 0.25,
        name: str = "loop_lag_s",
    ) -> None:
        self.metrics = metrics
        self.interval = interval
        self.name = name
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._handle: Optional[asyncio.TimerHandle] = None
        self._expected = 0.0
        self._running = False

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        self._loop = loop if loop is not None else asyncio.get_running_loop()
        self._running = True
        self._arm()

    def _arm(self) -> None:
        self._expected = time.monotonic() + self.interval
        self._handle = self._loop.call_later(self.interval, self._tick)

    def _tick(self) -> None:
        lag = max(0.0, time.monotonic() - self._expected)
        self.metrics.set_gauge(self.name, lag)
        self.metrics.observe(self.name, lag)
        if self._running:
            self._arm()

    def stop(self) -> None:
        self._running = False
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
