"""Metrics registry — counters, gauges, timers, JSON export.

The reference's only observability is ``print`` statements and a broken
``loss_history`` endpoint (SURVEY §5 "Metrics/logging"). This registry
backs the manager's ``GET /{name}/metrics`` endpoint and the engine's
per-round/per-wave timings. Pure Python, no deps, threadsafe enough for
the asyncio + ``to_thread`` training model (GIL-atomic dict ops plus a
lock around multi-field timer updates).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict

# ---------------------------------------------------------------------------
# Declared counter registry.
#
# Dashboards and alert rules key on exact counter names, so every
# counter incremented under baton_tpu/server/ must be declared here —
# batonlint rule BTL030 enforces it (the linter parses these literals
# with ast.literal_eval; keep them plain literals, no computed values).
# Counter FAMILIES whose suffix is built at runtime (f-strings keyed on
# an HTTP status, for example) declare their static prefix in
# DECLARED_COUNTER_PREFIXES instead.
DECLARED_COUNTERS = frozenset({
    # manager: recovery / lifecycle
    "recovery_rounds_aborted",
    "recovery_rounds_resumed",
    "clients_culled",
    "rounds_finished",
    "broadcast_timeout",
    # manager: downlink data plane
    "range_resumes",
    "bytes_broadcast",
    "blob_hits_delta",
    "blob_hits_full",
    # manager: uplink ingest / admission control
    "ingest_rejected_429",
    "uploads_rejected_413",
    "control_rejected_413",
    "bytes_uploaded",
    "duplicate_updates_deduped",
    "repeat_updates_ignored",
    "updates_received",
    "compressed_updates_received",
    "chunk_bytes_received",
    "chunked_uploads_assembled",
    # manager: secure aggregation
    "secure_rounds_aborted_keys",
    "secure_rounds_aborted_shares",
    "secure_rounds_unrecoverable",
    "secure_dropouts_recovered",
    # worker: secure aggregation downgrade guard
    "updates_refused_secure_downgrade",
    # worker: outbox / delivery
    "outbox_reloaded_from_disk",
    "updates_delivered",
    "update_retries",
    "update_backpressure_429",
    # worker: downlink blob fetch
    "blob_reused_anchor",
    "blob_fetch_delta",
    "blob_fetch_delta_chain",
    "blob_delta_digest_mismatch",
    "blob_fetch_failed",
    "blob_fetch_full",
    "blob_range_resumes",
    # worker: uplink chunked upload
    "chunk_upload_resumes",
    "chunk_bytes_resume_skipped",
    "chunk_bytes_put",
    # worker: control plane
    "broadcast_rejected_413",
    "train_epochs_completed",
})

DECLARED_COUNTER_PREFIXES = (
    "updates_abandoned_",   # worker: f"updates_abandoned_{status}"
    "broadcast_rejected_",  # manager: f"broadcast_rejected_{status}"
)


class _TimerStat:
    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)
        self.last = seconds

    def to_json(self) -> dict:
        return {
            "count": self.count,
            "total_s": self.total,
            "mean_s": self.total / self.count if self.count else 0.0,
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
            "last_s": self.last,
        }


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, _TimerStat] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        self._gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = _TimerStat()
            stat.observe(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: v.to_json() for k, v in self._timers.items()},
            }
