"""Metrics registry — counters, gauges, timers, JSON export.

The reference's only observability is ``print`` statements and a broken
``loss_history`` endpoint (SURVEY §5 "Metrics/logging"). This registry
backs the manager's ``GET /{name}/metrics`` endpoint and the engine's
per-round/per-wave timings. Pure Python, no deps, threadsafe enough for
the asyncio + ``to_thread`` training model (GIL-atomic dict ops plus a
lock around multi-field timer updates).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict


class _TimerStat:
    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)
        self.last = seconds

    def to_json(self) -> dict:
        return {
            "count": self.count,
            "total_s": self.total,
            "mean_s": self.total / self.count if self.count else 0.0,
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
            "last_s": self.last,
        }


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._timers: Dict[str, _TimerStat] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        self._gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = _TimerStat()
            stat.observe(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: v.to_json() for k, v in self._timers.items()},
            }
