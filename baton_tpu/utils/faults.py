"""HTTP-layer fault injection for elasticity testing.

The reference's failure handling (heartbeat/TTL cull, eager eviction,
401 re-register — SURVEY §3.3) was only ever exercised by manually
killing processes; there is no fault *injection* anywhere in its tree
(SURVEY §5). This module makes those paths testable deterministically:
an aiohttp middleware that, per matching route, can

* ``error`` — short-circuit with an HTTP status (e.g. 503 heartbeat
  outage, 404 "wrong client" to force re-registration),
* ``delay`` — sleep before proceeding (stragglers; exercises the
  round watchdog's partial aggregation),
* ``drop`` — abort the TCP transport with no response (connection
  reset; exercises the manager's eager-eviction path).

Rules fire a bounded number of ``times`` (default: forever) and record
every hit, so tests assert both the injected failure and the recovery.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Callable, List, Optional

from aiohttp import web


@dataclasses.dataclass
class Rule:
    match: str                      # substring of the request path
    action: str                     # "error" | "delay" | "drop"
    status: int = 503               # for "error"
    delay_s: float = 0.0            # for "delay"
    times: Optional[int] = None     # None = unlimited
    hits: int = 0
    # Optional dynamic guard: the rule only fires while gate() is truthy.
    # Lets a harness flip a standing rule on/off (e.g. an availability
    # curve toggling a worker's 503 refusal) without mutating the rule
    # list from another task mid-iteration.
    gate: Optional[Callable[[], bool]] = None

    def applies(self, path: str) -> bool:
        if self.match not in path:
            return False
        if self.times is not None and self.hits >= self.times:
            return False
        return self.gate is None or bool(self.gate())


def _match_target(request: web.Request) -> str:
    """Rules match against path + query string: worker requests carry
    their identity in the query (``update?client_id=...``), and per-client
    faults (drop ONE worker's uploads, not the route) need to see it."""
    return request.path_qs


class FaultInjector:
    """Attach to any app (manager or worker) at construction time:

        inj = FaultInjector()
        app = web.Application(middlewares=[inj.middleware])
        inj.error("heartbeat", status=503, times=2)
    """

    def __init__(self) -> None:
        self.rules: List[Rule] = []

        @web.middleware
        async def middleware(request: web.Request, handler):
            for rule in self.rules:
                if not rule.applies(_match_target(request)):
                    continue
                rule.hits += 1
                if rule.action == "error":
                    return web.json_response(
                        {"err": "injected fault"}, status=rule.status
                    )
                if rule.action == "delay":
                    await asyncio.sleep(rule.delay_s)
                elif rule.action == "drop":
                    if request.transport is not None:
                        request.transport.abort()
                    raise ConnectionResetError("injected connection drop")
            return await handler(request)

        self.middleware = middleware

    # ------------------------------------------------------------------
    def error(self, match: str, status: int = 503, times: Optional[int] = None,
              gate: Optional[Callable[[], bool]] = None) -> Rule:
        rule = Rule(match=match, action="error", status=status, times=times,
                    gate=gate)
        self.rules.append(rule)
        return rule

    def delay(self, match: str, seconds: float, times: Optional[int] = None,
              gate: Optional[Callable[[], bool]] = None) -> Rule:
        rule = Rule(match=match, action="delay", delay_s=seconds, times=times,
                    gate=gate)
        self.rules.append(rule)
        return rule

    def drop(self, match: str, times: Optional[int] = None,
             gate: Optional[Callable[[], bool]] = None) -> Rule:
        rule = Rule(match=match, action="drop", times=times, gate=gate)
        self.rules.append(rule)
        return rule

    def clear(self) -> None:
        self.rules.clear()

    def remove(self, rule: Rule) -> None:
        """Detach one rule (phase-scoped faults end with their phase)."""
        try:
            self.rules.remove(rule)
        except ValueError:
            pass
