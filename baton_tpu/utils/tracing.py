"""Dependency-free distributed span recorder with W3C ``traceparent``
propagation and Chrome ``trace_event`` export.

One federated round = one trace. The trace id is *derived* —
``sha256(f"{exp_name}/{round_name}")`` — so the manager, every worker,
and a post-crash manager incarnation all agree on it without any
coordination handshake: whoever touches the round can stamp spans into
the same trace, and a recovered manager resumes the trace its
predecessor started.

Spans cross process boundaries two ways:

- **downstream** (manager → worker): the standard ``traceparent``
  header (``00-<trace_id>-<span_id>-01``) rides every HTTP call made
  under an active span; ``trace_headers()`` builds the header dict and
  batonlint BTL031 enforces that outbound calls under a span use it.
- **upstream** (worker → manager): workers ship their *finished* spans
  as JSON to the manager's ``POST /{name}/trace_spans`` endpoint after
  delivering an update, and the manager's tracer :meth:`ingest`\\ s
  them, so ``GET /{name}/rounds/{rid}/trace`` serves the whole
  distributed round from one place.

Crash survivability: with ``spool_dir`` set, every span is appended to
``<spool_dir>/<trace_id>.jsonl`` **eagerly at span end** — a manager
killed mid-round loses its Python heap but not the spool, so the trace
exported by the recovered incarnation still shows the first
incarnation's spans and the recovery gap between them. Export merges
memory + spool, deduplicating on span id.

The active span travels via :mod:`contextvars`, so it follows awaits
and ``ensure_future`` task spawns (asyncio copies the context) without
any explicit plumbing.
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_TRACEPARENT_VERSION = "00"
_SPAN_KEYS = ("trace_id", "span_id", "parent_id", "name", "service",
              "start", "end", "args")

# (trace_id, span_id) of the active span in this task/thread context.
_current: contextvars.ContextVar[Optional[Tuple[str, str]]] = (
    contextvars.ContextVar("baton_trace", default=None)
)


# ---------------------------------------------------------------------------
# ids + traceparent
def make_trace_id(exp_name: str, round_name: str) -> str:
    """Deterministic 16-byte trace id for one round of one experiment."""
    digest = hashlib.sha256(f"{exp_name}/{round_name}".encode()).hexdigest()
    return digest[:32]


def root_span_id(trace_id: str) -> str:
    """Deterministic id for the round's root span, so phase spans can
    parent-link to it *before* the root is emitted (it is recorded
    retroactively at round end) and across manager incarnations."""
    return hashlib.sha256(f"{trace_id}/root".encode()).hexdigest()[:16]


def make_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"{_TRACEPARENT_VERSION}-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``traceparent`` → ``(trace_id, span_id)``, or None if malformed.
    Lenient on version/flags (future versions must still parse)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span, or None."""
    return _current.get()


def activate(trace_id: str, span_id: str) -> contextvars.Token:
    """Install a remote parent (e.g. from an incoming ``traceparent``)
    as the active span context; pair with :func:`deactivate`."""
    return _current.set((trace_id, span_id))


def deactivate(token: contextvars.Token) -> None:
    _current.reset(token)


def trace_headers(
    headers: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Header dict for an outbound HTTP call: the given headers plus
    ``traceparent`` for the active span (if any). batonlint BTL031
    requires outbound aiohttp calls made under an active span to build
    their headers through this helper."""
    out = dict(headers) if headers else {}
    ctx = _current.get()
    if ctx is not None:
        out["traceparent"] = format_traceparent(ctx[0], ctx[1])
    return out


# ---------------------------------------------------------------------------
class Span:
    """One timed operation. Finished (and recorded) via :meth:`end`;
    prefer ``with tracer.span(...)`` which ends on every path."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "service", "start", "args", "_token", "_ended")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 service, args) -> None:
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.service = service
        self.start = time.time()
        self.args: Dict[str, Any] = dict(args)
        self._token: Optional[contextvars.Token] = None
        self._ended = False

    def set(self, **kv: Any) -> None:
        self.args.update(kv)

    def end(self, end_time: Optional[float] = None) -> None:
        if self._ended:
            return
        self._ended = True
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self.tracer._record({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start": self.start,
            "end": end_time if end_time is not None else time.time(),
            "args": self.args,
        })


class Tracer:
    """In-process span recorder for one service (one manager or worker
    incarnation). ``service`` labels every span; give each incarnation
    a distinct label (``manager#a1b2``) so a chaos test's two managers
    are distinguishable inside one trace. Timestamps are wall-clock
    (``time.time()``) so spans from different processes align."""

    def __init__(
        self,
        service: str,
        spool_dir: Optional[str] = None,
        max_spans: int = 50_000,
    ) -> None:
        self.service = service
        self.spool_dir = spool_dir
        self.max_spans = max_spans
        self._lock = threading.Lock()
        # trace_id -> list of finished span dicts (insertion order)
        self._spans: Dict[str, List[dict]] = {}
        self._n_spans = 0
        if spool_dir:
            os.makedirs(spool_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def start_span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
        **args: Any,
    ) -> Span:
        """Manual span: caller owns closure on ALL paths (try/finally
        ``.end()`` — batonlint BTL031 checks this). Parent defaults to
        the active context; an explicit ``trace_id`` starts/joins that
        trace without touching the context."""
        ctx = _current.get()
        if trace_id is None:
            if ctx is not None:
                trace_id = ctx[0]
                if parent_id is None:
                    parent_id = ctx[1]
            else:
                trace_id = os.urandom(16).hex()
        elif parent_id is None and ctx is not None and ctx[0] == trace_id:
            parent_id = ctx[1]
        return Span(
            self, name, trace_id, span_id or make_span_id(), parent_id,
            self.service, args,
        )

    @contextmanager
    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        span_id: Optional[str] = None,
        **args: Any,
    ):
        """``with tracer.span("broadcast"): ...`` — activates the span
        as the context for nested spans and outbound ``trace_headers``
        calls, and ends it on every exit path."""
        sp = self.start_span(
            name, trace_id=trace_id, parent_id=parent_id, span_id=span_id,
            **args,
        )
        sp._token = _current.set((sp.trace_id, sp.span_id))
        try:
            yield sp
        except BaseException as exc:
            sp.set(error=type(exc).__name__)
            raise
        finally:
            sp.end()

    def record_span(
        self,
        name: str,
        trace_id: str,
        start: float,
        end: float,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        **args: Any,
    ) -> None:
        """Record an already-timed span directly — e.g. the round's
        ROOT span, emitted retroactively at round end with its
        deterministic :func:`root_span_id` so the phase spans recorded
        during the round (possibly by a different, crashed incarnation)
        are already parent-linked to it."""
        self._record({
            "trace_id": trace_id,
            "span_id": span_id or make_span_id(),
            "parent_id": parent_id,
            "name": name,
            "service": self.service,
            "start": float(start),
            "end": float(end),
            "args": dict(args),
        })

    # ------------------------------------------------------------------
    def _record(self, span: dict) -> None:
        with self._lock:
            if self._n_spans < self.max_spans:
                self._spans.setdefault(span["trace_id"], []).append(span)
                self._n_spans += 1
            if self.spool_dir:
                # EAGER append: a killed process loses the heap, not the
                # spool — this line is why a recovered manager can still
                # export its predecessor's half of the round
                path = os.path.join(
                    self.spool_dir, f"{span['trace_id']}.jsonl"
                )
                with open(path, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(span) + "\n")

    def ingest(self, spans: List[dict]) -> int:
        """Record already-finished spans shipped from another process
        (the worker → manager upstream path). Malformed entries are
        dropped, not raised; returns the accepted count."""
        accepted = 0
        for raw in spans:
            if not isinstance(raw, dict):
                continue
            try:
                span = {
                    "trace_id": str(raw["trace_id"]),
                    "span_id": str(raw["span_id"]),
                    "parent_id": (
                        str(raw["parent_id"])
                        if raw.get("parent_id") else None
                    ),
                    "name": str(raw["name"])[:200],
                    "service": str(raw.get("service", "remote"))[:100],
                    "start": float(raw["start"]),
                    "end": float(raw["end"]),
                    "args": (
                        dict(raw["args"])
                        if isinstance(raw.get("args"), dict) else {}
                    ),
                }
            except (KeyError, TypeError, ValueError):
                continue
            if len(span["trace_id"]) != 32 or len(span["span_id"]) != 16:
                continue
            self._record(span)
            accepted += 1
        return accepted

    # ------------------------------------------------------------------
    def spans_for(self, trace_id: str) -> List[dict]:
        """All recorded spans for one trace: memory ∪ spool, deduped on
        span id (memory wins; a respooled duplicate is identical)."""
        with self._lock:
            spans = list(self._spans.get(trace_id, ()))
        seen = {s["span_id"] for s in spans}
        if self.spool_dir:
            path = os.path.join(self.spool_dir, f"{trace_id}.jsonl")
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            span = json.loads(line)
                        except ValueError:
                            continue  # torn tail write from a kill
                        sid = span.get("span_id")
                        if sid and sid not in seen:
                            seen.add(sid)
                            spans.append(span)
            except OSError:
                pass
        return sorted(spans, key=lambda s: s.get("start", 0.0))

    def export(self, trace_id: str) -> dict:
        """Chrome ``trace_event`` JSON for one trace — load the result
        straight into Perfetto / chrome://tracing. Each service becomes
        a named process; spans are complete ("X") events in µs."""
        spans = self.spans_for(trace_id)
        pids: Dict[str, int] = {}
        events: List[dict] = []
        for span in spans:
            service = span.get("service", "unknown")
            if service not in pids:
                pids[service] = len(pids) + 1
                events.append({
                    "ph": "M", "pid": pids[service], "tid": 0,
                    "name": "process_name", "args": {"name": service},
                })
            args = dict(span.get("args") or {})
            args["span_id"] = span["span_id"]
            if span.get("parent_id"):
                args["parent_id"] = span["parent_id"]
            events.append({
                "ph": "X",
                "pid": pids[service],
                "tid": 0,
                "name": span.get("name", "?"),
                "cat": "baton",
                "ts": span.get("start", 0.0) * 1e6,
                "dur": max(
                    0.0,
                    (span.get("end", 0.0) - span.get("start", 0.0)) * 1e6,
                ),
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def drain(self, trace_id: Optional[str] = None) -> List[dict]:
        """Pop finished spans from memory (the worker's shipping path).
        With a trace id: that trace's spans; without: everything."""
        with self._lock:
            if trace_id is None:
                out = [s for lst in self._spans.values() for s in lst]
                self._spans.clear()
            else:
                out = self._spans.pop(trace_id, [])
            self._n_spans -= len(out)
        return out


def gc_spool(
    spool_dir: Optional[str],
    *,
    max_age_s: float = 3600.0,
    max_files: int = 512,
    exempt=(),
    now: Optional[float] = None,
) -> int:
    """Bound the span spool: the spool grows one ``.jsonl`` per trace
    forever, so a retention tick deletes files older than ``max_age_s``
    (by mtime) and then the oldest beyond ``max_files`` — except traces
    in ``exempt`` (ids a retained forensics bundle still references;
    deleting those would hollow out served evidence). Returns the number
    of files removed; every error is ignored, retention is advisory."""
    if not spool_dir or not os.path.isdir(spool_dir):
        return 0
    now = time.time() if now is None else now
    exempt = set(exempt)
    entries = []  # (mtime, path, trace_id)
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return 0
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        trace_id = name[:-len(".jsonl")]
        if trace_id in exempt:
            continue
        path = os.path.join(spool_dir, name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        entries.append((mtime, path, trace_id))
    entries.sort()  # oldest first
    removed = 0
    keep = []
    for mtime, path, trace_id in entries:
        if max_age_s is not None and now - mtime > max_age_s:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
        else:
            keep.append(path)
    excess = len(keep) - max(0, int(max_files))
    for path in keep[:max(0, excess)]:
        try:
            os.remove(path)
            removed += 1
        except OSError:
            pass
    return removed
