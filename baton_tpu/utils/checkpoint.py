"""Round-granular checkpoint/resume on orbax.

The reference keeps the global model only in manager memory — a restart
loses everything and workers silently retrain from scratch via the 401
re-register path (SURVEY §5 "Checkpoint/resume: absent"). Here the full
experiment state — global params, server optimizer state (FedOpt), round
counter, loss history — is written atomically per round with
``orbax.checkpoint`` and restored on boot, so a manager restart resumes
the federation where it stopped.

Orbax is the TPU-native choice: it writes sharded ``jax.Array``s
directly from device memory (no host gather for replicated/sharded
trees) and is the standard JAX ecosystem format.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

Params = Any


@dataclasses.dataclass
class RestoredState:
    """What :meth:`Checkpointer.restore` hands back."""

    step: int
    params: Params
    server_opt_state: Any
    meta: dict
    extra: Any = None


class Checkpointer:
    """Save/restore federated experiment state per round.

    ``directory`` is created if needed; ``max_to_keep`` old steps are
    retained (older ones garbage-collected by orbax). All saves are
    synchronous by default — a checkpoint either fully exists or not at
    all (orbax writes to a temp dir and renames).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    # ------------------------------------------------------------------
    def save(
        self,
        step: int,
        params: Params,
        server_opt_state: Any = None,
        meta: Optional[dict] = None,
        wait: bool = True,
        extra: Any = None,
    ) -> None:
        """``extra`` is any additional pytree riding the checkpoint —
        the slot for federation-mode state the globals don't capture:
        a FedPer personal stack, StatefulClients optimizer states, or
        ClusteredFedSim cluster params (all plain pytrees)."""
        ocp = self._ocp
        items = {
            "params": ocp.args.StandardSave(params),
            "meta": ocp.args.JsonSave(meta or {}),
        }
        if server_opt_state is not None:
            items["server_opt"] = ocp.args.StandardSave(server_opt_state)
        if extra is not None:
            items["extra"] = ocp.args.StandardSave(extra)
        self._mngr.save(step, args=ocp.args.Composite(**items))
        if wait:
            self._mngr.wait_until_finished()

    # ------------------------------------------------------------------
    def _saved_items(self, step: int) -> set:
        """Names of the items stored at ``step``."""
        try:
            meta = self._mngr.item_metadata(step)
            return {k for k in meta.keys() if meta[k] is not None}
        except Exception:
            # fallback: orbax lays out one subdirectory per item
            step_dir = os.path.join(self.directory, str(step))
            if os.path.isdir(step_dir):
                return {
                    d for d in os.listdir(step_dir)
                    if os.path.isdir(os.path.join(step_dir, d))
                }
            return set()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def restore(
        self,
        params_template: Params,
        server_opt_template: Any = None,
        step: Optional[int] = None,
        extra_template: Any = None,
    ) -> Optional[RestoredState]:
        """Restore ``step`` (default: latest). Returns None when the
        directory holds no checkpoints — callers fall through to fresh
        init. Templates supply the pytree structure/shape/dtype/sharding
        to restore into."""
        ocp = self._ocp
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        items = {
            "params": ocp.args.StandardRestore(params_template),
            "meta": ocp.args.JsonRestore(),
        }
        saved = self._saved_items(step)
        if server_opt_template is not None and "server_opt" in saved:
            # Only request server_opt when the checkpoint actually holds
            # one — e.g. the HTTP manager's end_round never saves server
            # optimizer state, and pointing a FedOpt-configured run at
            # such a checkpoint must fall back to fresh optimizer state,
            # not raise.
            items["server_opt"] = ocp.args.StandardRestore(server_opt_template)
        if extra_template is not None and "extra" in saved:
            items["extra"] = ocp.args.StandardRestore(extra_template)
        restored = self._mngr.restore(step, args=ocp.args.Composite(**items))
        return RestoredState(
            step=step,
            params=restored["params"],
            server_opt_state=restored.get("server_opt"),
            meta=restored["meta"] or {},
            extra=restored.get("extra"),
        )

    # ------------------------------------------------------------------
    def close(self) -> None:
        self._mngr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
