"""Structured JSON logging + the per-round SLO record appender.

Two small, dependency-free pieces:

- :class:`JsonFormatter` / :func:`setup_json_logging` — one JSON object
  per log line with ``trace_id``/``span_id`` correlation fields pulled
  from the active tracing context (:mod:`baton_tpu.utils.tracing`), so
  a grep for a round's trace id yields its logs across manager and
  workers.
- :class:`RoundsLog` — thread-safe appender for ``rounds.jsonl``, the
  per-round SLO summary artifact (one JSON object per finished/aborted
  round) that the ROADMAP's scenario harness consumes. Appends are a
  few hundred bytes once per round; they are written inline under a
  lock with an fsync-free flush.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from baton_tpu.utils import tracing

_RESERVED = frozenset(
    logging.LogRecord(
        "", 0, "", 0, "", (), None
    ).__dict__
) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg, any ``extra``
    fields, plus trace/span correlation from the active span context."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        ctx = tracing.current_context()
        if ctx is not None:
            out["trace_id"], out["span_id"] = ctx
        for key, val in record.__dict__.items():
            if key not in _RESERVED and not key.startswith("_"):
                try:
                    json.dumps(val)
                except (TypeError, ValueError):
                    val = repr(val)
                out[key] = val
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=repr)


def setup_json_logging(
    logger: Optional[logging.Logger] = None,
    level: int = logging.INFO,
    stream: Any = None,
) -> logging.Handler:
    """Attach a JSON-formatted stream handler (idempotent per logger:
    an existing JsonFormatter handler is reused)."""
    logger = logger if logger is not None else logging.getLogger("baton_tpu")
    for handler in logger.handlers:
        if isinstance(handler.formatter, JsonFormatter):
            return handler
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter())
    logger.addHandler(handler)
    logger.setLevel(level)
    return handler


class RoundsLog:
    """Append-only ``rounds.jsonl`` writer. Each record is one round's
    SLO summary (see :meth:`baton_tpu.server.http_manager.Experiment`'s
    ``_emit_slo_record`` for the schema); ``wall_ts`` is stamped here
    so callers never race the clock."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def append(self, record: Dict[str, Any]) -> None:
        # Crash-safety: the full line (terminator included) goes down in
        # ONE write() followed by a flush, so a reader racing the writer
        # — or a crash mid-record — can tear at most the final line,
        # never interleave two records.
        data = json.dumps(
            dict(record, wall_ts=round(time.time(), 6)), default=repr
        ) + "\n"
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(data)
                fh.flush()

    def read_all(self) -> list:
        """Parse every record back (test/harness convenience). Torn or
        malformed lines are skipped, not raised; use
        :func:`read_rounds_jsonl` when the torn-line count matters."""
        return read_rounds_jsonl(self.path)[0]

    def maybe_rotate(self, max_bytes: int) -> bool:
        """Size-bounded rotation under the appender's own lock: when the
        file exceeds ``max_bytes`` it moves to ``<path>.1`` (replacing
        any previous rotation) and appends restart fresh. One
        generation is enough — readers are torn-line-tolerant and the
        SLO harness consumes the live file within a run."""
        return maybe_rotate_jsonl(self.path, max_bytes, lock=self._lock)


def maybe_rotate_jsonl(
    path: str,
    max_bytes: int,
    lock: Optional[threading.Lock] = None,
) -> bool:
    """Rotate ``path`` to ``path.1`` when it exceeds ``max_bytes``
    (``os.replace`` — atomic on POSIX). Returns True when a rotation
    happened. Advisory: every OS error is swallowed, a retention tick
    must never take its owner down."""
    if not max_bytes or max_bytes <= 0:
        return False
    ctx = lock if lock is not None else threading.Lock()
    with ctx:
        try:
            if os.path.getsize(path) <= max_bytes:
                return False
            os.replace(path, path + ".1")
            return True
        except OSError:
            return False


def read_rounds_jsonl(path: str) -> tuple:
    """Tolerant ``rounds.jsonl`` reader: returns ``(records, n_torn)``.

    A crash mid-append (or a reader racing the writer's final line) can
    leave a torn trailing line; report it rather than raising so an SLO
    evaluation over a crashed run still sees every complete record.
    """
    records, n_torn = [], 0
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    n_torn += 1
    except OSError:
        pass
    return records, n_torn
