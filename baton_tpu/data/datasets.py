"""Dataset loaders for the BASELINE configs (CIFAR-10, MNIST, AG-News).

The reference has no data layer at all — its demo synthesizes
``y = p·X`` batches inline (reference demo.py:52-59). The north-star
configs (BASELINE.md 1-3) name real datasets, so this module loads them
from their *standard on-disk formats*:

* CIFAR-10 — the original ``cifar-10-batches-py`` pickled batches, or a
  consolidated ``cifar10.npz``;
* MNIST — the classic IDX ``*-ubyte[.gz]`` files, or ``mnist.npz``;
* AG-News — ``train.csv``/``test.csv`` (class,title,description rows).

``download=True`` fetches the canonical archives when the environment
has network access. Air-gapped environments (like the TPU CI container,
which has zero egress) either provide ``data_dir`` with pre-fetched
files or opt into ``fallback="synthetic"``: a deterministic,
class-conditional surrogate with the exact shapes/dtypes of the real
dataset, clearly labelled in the returned metadata — convergence and
accuracy are measurable, but numbers from it must not be quoted as
real-dataset results.

One loader needs neither network nor staged files:
:func:`load_digits_real` — the UCI handwritten-digits images bundled
inside scikit-learn — giving the air-gapped container REAL bytes to
train on (tests/test_datasets.py::test_digits_real_federated_accuracy
holds a real-data accuracy bar on non-IID shards of it).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
import urllib.request
from typing import Dict, Optional, Tuple

import numpy as np

Arrays = Dict[str, np.ndarray]

_CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
_MNIST_URLS = {
    "train_images": "https://storage.googleapis.com/cvdf-datasets/mnist/train-images-idx3-ubyte.gz",
    "train_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/train-labels-idx1-ubyte.gz",
    "test_images": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-images-idx3-ubyte.gz",
    "test_labels": "https://storage.googleapis.com/cvdf-datasets/mnist/t10k-labels-idx1-ubyte.gz",
}

DEFAULT_CACHE = os.path.expanduser("~/.cache/baton_tpu/datasets")


class DatasetUnavailable(RuntimeError):
    """Raised when a real dataset is not on disk and cannot be fetched."""


def _fetch(url: str, dest: str) -> str:
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = dest + ".part"
    urllib.request.urlretrieve(url, tmp)  # noqa: S310 — canonical dataset hosts
    os.replace(tmp, dest)
    return dest


# ======================================================================
# CIFAR-10


def load_cifar10(
    data_dir: Optional[str] = None,
    download: bool = False,
    fallback: Optional[str] = None,
    seed: int = 0,
) -> Tuple[Arrays, Arrays, Dict]:
    """Returns ``(train, test, info)`` with ``train/test = {"x": float32
    [N,32,32,3] in [0,1], "y": int32 [N]}``.

    Resolution order: ``cifar10.npz`` → ``cifar-10-batches-py/`` →
    (``download=True``) fetch official archive → (``fallback='synthetic'``)
    deterministic surrogate → raise :class:`DatasetUnavailable`.
    """
    data_dir = data_dir or os.path.join(DEFAULT_CACHE, "cifar10")
    npz = os.path.join(data_dir, "cifar10.npz")
    batches = os.path.join(data_dir, "cifar-10-batches-py")

    if os.path.exists(npz):
        z = np.load(npz)

        def norm(x):
            x = x.astype(np.float32)
            # uint8-stored archives hold 0..255; honor the [0,1] contract
            return x / 255.0 if x.max() > 1.5 else x

        return (
            {"x": norm(z["x_train"]), "y": z["y_train"].astype(np.int32)},
            {"x": norm(z["x_test"]), "y": z["y_test"].astype(np.int32)},
            {"name": "cifar10", "synthetic": False, "source": npz},
        )

    if not os.path.isdir(batches) and download:
        archive = os.path.join(data_dir, "cifar-10-python.tar.gz")
        try:
            if not os.path.exists(archive):
                _fetch(_CIFAR10_URL, archive)
            with tarfile.open(archive, "r:gz") as tf:
                tf.extractall(data_dir, filter="data")
        except Exception as exc:  # zero-egress / bad mirror
            if fallback != "synthetic":
                raise DatasetUnavailable(
                    f"CIFAR-10 download failed ({exc}); provide data_dir or "
                    "fallback='synthetic'"
                ) from exc

    if os.path.isdir(batches):
        def read_batch(fname):
            with open(os.path.join(batches, fname), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return x, np.asarray(d[b"labels"])

        xs, ys = zip(*[read_batch(f"data_batch_{i}") for i in range(1, 6)])
        x_train = np.concatenate(xs).astype(np.float32) / 255.0
        y_train = np.concatenate(ys).astype(np.int32)
        x_test, y_test = read_batch("test_batch")
        return (
            {"x": x_train, "y": y_train},
            {"x": x_test.astype(np.float32) / 255.0,
             "y": y_test.astype(np.int32)},
            {"name": "cifar10", "synthetic": False, "source": batches},
        )

    if fallback == "synthetic":
        train = synthetic_image_classification(
            50_000, (32, 32, 3), 10, seed=seed)
        test = synthetic_image_classification(
            10_000, (32, 32, 3), 10, seed=seed + 1)
        return train, test, {"name": "cifar10", "synthetic": True,
                             "source": "synthetic-surrogate"}

    raise DatasetUnavailable(
        f"CIFAR-10 not found under {data_dir}; pass download=True (needs "
        "network) or fallback='synthetic'"
    )


# ======================================================================
# MNIST


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


_MNIST_STEMS = {
    "train_images": ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
    "train_labels": ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
    "test_images": ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"),
    "test_labels": ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"),
}


def load_mnist(
    data_dir: Optional[str] = None,
    download: bool = False,
    fallback: Optional[str] = None,
    seed: int = 0,
) -> Tuple[Arrays, Arrays, Dict]:
    """Returns ``(train, test, info)`` with ``x`` float32 [N,28,28,1]."""
    data_dir = data_dir or os.path.join(DEFAULT_CACHE, "mnist")
    npz = os.path.join(data_dir, "mnist.npz")
    if os.path.exists(npz):
        z = np.load(npz)
        def norm(x):
            x = x.astype(np.float32) / (255.0 if x.max() > 1.5 else 1.0)
            return x.reshape(x.shape[0], 28, 28, 1)
        return (
            {"x": norm(z["x_train"]), "y": z["y_train"].astype(np.int32)},
            {"x": norm(z["x_test"]), "y": z["y_test"].astype(np.int32)},
            {"name": "mnist", "synthetic": False, "source": npz},
        )

    def find(kind):
        for stem in _MNIST_STEMS[kind]:
            for suffix in (".gz", ""):
                p = os.path.join(data_dir, stem + suffix)
                if os.path.exists(p):
                    return p
        return None

    if find("train_images") is None and download:
        try:
            for kind, url in _MNIST_URLS.items():
                dest = os.path.join(data_dir, _MNIST_STEMS[kind][0] + ".gz")
                if not os.path.exists(dest):
                    _fetch(url, dest)
        except Exception as exc:
            if fallback != "synthetic":
                raise DatasetUnavailable(
                    f"MNIST download failed ({exc}); provide data_dir or "
                    "fallback='synthetic'"
                ) from exc

    if find("train_images") is not None:
        def split(kind_img, kind_lbl):
            x = _read_idx(find(kind_img)).astype(np.float32) / 255.0
            return {
                "x": x[..., None],
                "y": _read_idx(find(kind_lbl)).astype(np.int32),
            }
        return (
            split("train_images", "train_labels"),
            split("test_images", "test_labels"),
            {"name": "mnist", "synthetic": False, "source": data_dir},
        )

    if fallback == "synthetic":
        train = synthetic_image_classification(60_000, (28, 28, 1), 10, seed=seed)
        test = synthetic_image_classification(10_000, (28, 28, 1), 10, seed=seed + 1)
        return train, test, {"name": "mnist", "synthetic": True,
                             "source": "synthetic-surrogate"}

    raise DatasetUnavailable(
        f"MNIST not found under {data_dir}; pass download=True (needs "
        "network) or fallback='synthetic'"
    )


# ======================================================================
# AG-News (text classification, 4 classes)


def load_ag_news(
    data_dir: Optional[str] = None,
    max_len: int = 128,
    fallback: Optional[str] = None,
    seed: int = 0,
) -> Tuple[Arrays, Arrays, Dict]:
    """Returns ``(train, test, info)`` with ``x`` int32 [N, max_len]
    byte-tokenized text (:class:`ByteTokenizer`) and ``y`` int32 [N] in
    [0, 4). Expects ``train.csv``/``test.csv`` in the AG-News release
    format: ``"class","title","description"`` with classes 1-4."""
    data_dir = data_dir or os.path.join(DEFAULT_CACHE, "ag_news")
    train_csv = os.path.join(data_dir, "train.csv")
    test_csv = os.path.join(data_dir, "test.csv")
    tok = ByteTokenizer(max_len=max_len)

    if os.path.exists(train_csv) and os.path.exists(test_csv):
        def read(path):
            import csv

            xs, ys = [], []
            with open(path, newline="", encoding="utf-8") as f:
                for row in csv.reader(f):
                    if not row:
                        continue
                    label = int(row[0]) - 1
                    text = ". ".join(row[1:])
                    xs.append(tok.encode(text))
                    ys.append(label)
            return {"x": np.stack(xs), "y": np.asarray(ys, np.int32)}

        return (read(train_csv), read(test_csv),
                {"name": "ag_news", "synthetic": False, "source": data_dir,
                 "vocab_size": tok.vocab_size})

    if fallback == "synthetic":
        train = synthetic_text_classification(8_000, max_len, 4, tok, seed=seed)
        test = synthetic_text_classification(1_000, max_len, 4, tok, seed=seed + 1)
        return train, test, {"name": "ag_news", "synthetic": True,
                             "source": "synthetic-surrogate",
                             "vocab_size": tok.vocab_size}

    raise DatasetUnavailable(
        f"AG-News train.csv/test.csv not found under {data_dir}; "
        "fetch the release CSVs there or pass fallback='synthetic'"
    )


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes, 256 is PAD.

    No merges, no external vocab files — deterministic, air-gap-safe,
    and adequate for classification fine-tunes (BASELINE config 3)."""

    PAD = 256

    def __init__(self, max_len: int = 128):
        self.max_len = max_len

    @property
    def vocab_size(self) -> int:
        return 257

    def encode(self, text: str) -> np.ndarray:
        raw = np.frombuffer(text.encode("utf-8")[: self.max_len], np.uint8)
        out = np.full((self.max_len,), self.PAD, np.int32)
        out[: raw.size] = raw
        return out

    def decode(self, ids) -> str:
        ids = np.asarray(ids)
        ids = ids[ids != self.PAD]
        return bytes(ids.astype(np.uint8)).decode("utf-8", errors="replace")

    def mask(self, ids) -> np.ndarray:
        """1.0 where a real token, 0.0 on padding — feeds attention bias
        / loss masks."""
        return (np.asarray(ids) != self.PAD).astype(np.float32)


# ======================================================================
# digits — REAL image data with zero egress


def load_digits_real(test_fraction: float = 0.2, seed: int = 0
                     ) -> Tuple[Arrays, Arrays, Dict]:
    """The UCI/NIST handwritten-digits dataset bundled INSIDE
    scikit-learn: 1797 real 8x8 grayscale digit images — the one real
    image dataset available in an air-gapped container. Returns
    ``(train, test, info)`` with ``x`` float32 [N, 8, 8, 1] in [0, 1]
    and ``y`` int32 [N], deterministically split.

    This exists so at least one recorded training run uses REAL bytes
    (every other loader needs network or pre-staged files and otherwise
    falls back to labelled synthetic surrogates). The loader contract is
    pinned in tests/test_datasets.py; the real-data accuracy bar lives
    with the canonical recipe (examples/10_real_digits.py, run by
    tests/test_examples.py::test_real_digits).
    """
    try:
        from sklearn.datasets import load_digits as _ld
    except ImportError as e:
        raise DatasetUnavailable("scikit-learn not installed") from e
    d = _ld()
    x = (d.data.astype(np.float32) / 16.0).reshape(-1, 8, 8, 1)
    y = d.target.astype(np.int32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(y))
    x, y = x[order], y[order]
    n_test = int(len(y) * test_fraction)
    test = {"x": x[:n_test], "y": y[:n_test]}
    train = {"x": x[n_test:], "y": y[n_test:]}
    info = {
        "dataset": "sklearn_digits",
        "real": True,
        "n_train": len(train["y"]),
        "n_test": n_test,
        "source": "scikit-learn bundled data (UCI optical digits)",
    }
    return train, test, info


# ======================================================================
# deterministic synthetic surrogates (clearly labelled as such)


def synthetic_image_classification(
    n: int, shape: Tuple[int, ...], n_classes: int, seed: int = 0
) -> Arrays:
    """Class-conditional Gaussian images: per-class prototype + noise.
    Learnable (a CNN separates the classes), shaped like the real thing."""
    rng = np.random.default_rng(seed)
    protos = rng.normal(0.5, 0.25, size=(n_classes,) + shape).astype(np.float32)
    y = rng.integers(0, n_classes, size=(n,)).astype(np.int32)
    x = protos[y] + rng.normal(0, 0.35, size=(n,) + shape).astype(np.float32)
    return {"x": np.clip(x, 0.0, 1.0), "y": y}


def synthetic_text_classification(
    n: int, max_len: int, n_classes: int, tok: ByteTokenizer, seed: int = 0
) -> Arrays:
    """Class-conditional token distributions over the byte vocab."""
    rng = np.random.default_rng(seed)
    class_words = [
        [f"w{c}_{i}" for i in range(12)] for c in range(n_classes)
    ]
    common = [f"the{i}" for i in range(8)]
    xs, ys = [], []
    for _ in range(n):
        c = int(rng.integers(0, n_classes))
        words = rng.choice(class_words[c] + common, size=12)
        xs.append(tok.encode(" ".join(words)))
        ys.append(c)
    return {"x": np.stack(xs), "y": np.asarray(ys, np.int32)}
