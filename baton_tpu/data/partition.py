"""Dataset partitioners: IID and Dirichlet non-IID label-skew shards.

Not in the reference (each baton worker invents its own data,
demo.py:52-59); required by the BASELINE configs ("128 non-IID clients
(Dirichlet shards)"). The Dirichlet scheme is the standard label-skew
protocol: for each client draw p ~ Dir(alpha·1_K) over classes and sample
its shard accordingly; alpha→∞ is IID, alpha→0 is one-class clients.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def iid_partition(
    data: Dict[str, np.ndarray], n_clients: int, rng: np.random.Generator
) -> List[Dict[str, np.ndarray]]:
    n = next(iter(data.values())).shape[0]
    perm = rng.permutation(n)
    shards = np.array_split(perm, n_clients)
    return [{k: v[idx] for k, v in data.items()} for idx in shards]


def dirichlet_partition(
    data: Dict[str, np.ndarray],
    n_clients: int,
    rng: np.random.Generator,
    alpha: float = 0.5,
    label_key: str = "y",
    min_samples: int = 1,
) -> List[Dict[str, np.ndarray]]:
    """Label-skew Dirichlet partition of a labelled dataset."""
    y = np.asarray(data[label_key])
    classes = np.unique(y)
    idx_by_class = {c: rng.permutation(np.flatnonzero(y == c)) for c in classes}
    client_indices: List[List[int]] = [[] for _ in range(n_clients)]
    for c in classes:
        idx = idx_by_class[c]
        props = rng.dirichlet(np.full(n_clients, alpha))
        # convert proportions to contiguous split points over this class
        cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
        for client_i, chunk in enumerate(np.split(idx, cuts)):
            client_indices[client_i].extend(chunk.tolist())
    # Rebalance BEFORE materializing any shard so stolen rows move (not
    # duplicate) between clients.
    for ci in client_indices:
        if len(ci) < min_samples:
            largest = max(range(n_clients), key=lambda i: len(client_indices[i]))
            need = min_samples - len(ci)
            ci.extend(client_indices[largest][-need:])
            del client_indices[largest][-need:]
    shards = []
    for ci in client_indices:
        arr = np.asarray(ci, dtype=np.int64)
        rng.shuffle(arr)
        shards.append({k: v[arr] for k, v in data.items()})
    return shards


def partition_stats(shards: List[Dict[str, np.ndarray]], label_key: str = "y"):
    """Per-shard (size, label histogram) — observability for non-IID runs."""
    stats = []
    for s in shards:
        y = np.asarray(s[label_key])
        vals, counts = np.unique(y, return_counts=True)
        stats.append({"n": int(y.shape[0]), "labels": dict(zip(vals.tolist(), counts.tolist()))})
    return stats


def label_shard_partition(
    data: Dict[str, np.ndarray],
    n_clients: int,
    rng: np.random.Generator,
    classes_per_client: int = 2,
    label_key: str = "y",
) -> List[Dict[str, np.ndarray]]:
    """The FedAvg paper's "pathological non-IID" split: sort by label,
    cut into ``n_clients * classes_per_client`` equal shards, deal each
    client ``classes_per_client`` shards — so most clients see only a
    couple of classes. Harsher than a Dirichlet skew; the classic
    stress test for aggregation/personalization methods."""
    if classes_per_client < 1:
        raise ValueError("classes_per_client must be >= 1")
    y = np.asarray(data[label_key])
    n = len(y)
    n_shards = n_clients * classes_per_client
    if n_shards > n:
        raise ValueError(
            f"{n_shards} shards requested from {n} samples"
        )
    # sort by label with a random tie-break so repeated calls differ
    order = np.lexsort((rng.random(n), y))
    shard_bounds = np.linspace(0, n, n_shards + 1).astype(int)
    shard_ids = rng.permutation(n_shards)
    out: List[Dict[str, np.ndarray]] = []
    for c in range(n_clients):
        mine = shard_ids[c * classes_per_client:(c + 1) * classes_per_client]
        idx = np.concatenate(
            [order[shard_bounds[s]:shard_bounds[s + 1]] for s in mine]
        )
        out.append({k: np.asarray(v)[idx] for k, v in data.items()})
    return out
