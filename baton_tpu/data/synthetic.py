"""Synthetic datasets.

``linear_client_data`` mirrors the reference demo's per-client data draw:
``32·randint(5,20)`` samples of ``y = p·X`` for a fixed 10-dim coefficient
vector (reference: demo.py:52-59) — including the ragged per-client sizes
that exercise the padding/masking machinery.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# The reference demo's fixed coefficient vector (demo.py:55).
DEMO_COEF = np.array([11, 5, 3, 2, 5, 6, 2, 7, 8, 1], dtype=np.float32)


def linear_client_data(
    rng: np.random.Generator,
    coef: Optional[np.ndarray] = None,
    noise: float = 0.0,
    min_batches: int = 5,
    max_batches: int = 20,
    batch_size: int = 32,
):
    """One client's dataset: ``{"x","y"}`` with 32·U[5,20] rows."""
    coef = DEMO_COEF if coef is None else np.asarray(coef, np.float32)
    n = batch_size * int(rng.integers(min_batches, max_batches + 1))
    x = rng.standard_normal((n, coef.shape[0])).astype(np.float32)
    y = x @ coef
    if noise:
        y = y + noise * rng.standard_normal(n).astype(np.float32)
    return {"x": x, "y": y.astype(np.float32)}


def synthetic_classification_clients(
    rng: np.random.Generator,
    n_clients: int,
    n_per_client: int = 128,
    in_dim: int = 32,
    n_classes: int = 10,
    ragged: bool = True,
) -> Tuple[list, np.ndarray]:
    """Linearly-separable-ish classification shards for engine tests."""
    w = rng.standard_normal((in_dim, n_classes)).astype(np.float32)
    datasets = []
    for _ in range(n_clients):
        n = n_per_client
        if ragged:
            n = int(rng.integers(n_per_client // 2, n_per_client + 1))
        x = rng.standard_normal((n, in_dim)).astype(np.float32)
        logits = x @ w + 0.5 * rng.standard_normal((n, n_classes)).astype(np.float32)
        y = np.argmax(logits, axis=-1).astype(np.int32)
        datasets.append({"x": x, "y": y})
    return datasets, w


def synthetic_image_clients(
    rng: np.random.Generator,
    n_clients: int,
    n_per_client: int = 64,
    image_size: int = 28,
    channels: int = 1,
    n_classes: int = 10,
):
    """MNIST-shaped synthetic image shards (class-dependent mean patches)."""
    protos = rng.standard_normal((n_classes, image_size, image_size, channels)).astype(
        np.float32
    )
    datasets = []
    for _ in range(n_clients):
        y = rng.integers(0, n_classes, size=n_per_client).astype(np.int32)
        x = protos[y] + 0.5 * rng.standard_normal(
            (n_per_client, image_size, image_size, channels)
        ).astype(np.float32)
        datasets.append({"x": x, "y": y})
    return datasets


def synthetic_char_clients(
    rng: np.random.Generator,
    n_clients: int,
    n_per_client: int = 32,
    seq_len: int = 32,
    vocab_size: int = 90,
    order: int = 2,
):
    """Shakespeare-shaped non-IID char-LM shards (models/lstm.py).

    Each client is a distinct "speaking role": its text is drawn from a
    client-specific order-``order`` Markov chain over the character
    alphabet, so clients share structure (a common base chain) but
    differ in style (per-client perturbation) — the non-IID shape of
    the FedAvg paper's role-per-client Shakespeare split. Sequences are
    next-char pairs: ``y`` is ``x`` shifted by one.
    """
    base = rng.dirichlet(np.full(vocab_size, 0.3), size=vocab_size ** order)
    datasets = []
    for _ in range(n_clients):
        style = rng.dirichlet(np.full(vocab_size, 0.5), size=vocab_size ** order)
        probs = 0.7 * base + 0.3 * style
        # per-state CDF once, then one searchsorted per char: rng.choice
        # re-validates p on every call — tens of seconds at example 07's
        # full scale (64 clients x ~20k chars)
        cdf = np.cumsum(probs, axis=1)
        uniforms = rng.random(n_per_client * seq_len + 1)
        text_len = n_per_client * seq_len + 1
        text = np.empty(text_len, np.int64)
        text[:order] = rng.integers(0, vocab_size, order)
        state = 0
        for i in range(order):
            state = state * vocab_size + int(text[i])
        for i in range(order, text_len):
            c = int(np.searchsorted(cdf[state], uniforms[i], side="right"))
            text[i] = min(c, vocab_size - 1)
            state = (state * vocab_size + int(text[i])) % (vocab_size ** order)
        xs = text[: n_per_client * seq_len].reshape(n_per_client, seq_len)
        ys = text[1: n_per_client * seq_len + 1].reshape(n_per_client, seq_len)
        datasets.append({"x": xs.astype(np.int32), "y": ys.astype(np.int32)})
    return datasets
