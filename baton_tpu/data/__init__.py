from baton_tpu.data.synthetic import (
    linear_client_data,
    synthetic_classification_clients,
)
from baton_tpu.data.partition import iid_partition, dirichlet_partition

__all__ = [
    "linear_client_data",
    "synthetic_classification_clients",
    "iid_partition",
    "dirichlet_partition",
]
