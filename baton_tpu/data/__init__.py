from baton_tpu.data.synthetic import (
    linear_client_data,
    synthetic_char_clients,
    synthetic_classification_clients,
)
from baton_tpu.data.partition import (
    dirichlet_partition,
    iid_partition,
    label_shard_partition,
)
from baton_tpu.data.datasets import (
    ByteTokenizer,
    DatasetUnavailable,
    load_ag_news,
    load_cifar10,
    load_digits_real,
    load_mnist,
)

__all__ = [
    "linear_client_data",
    "synthetic_char_clients",
    "synthetic_classification_clients",
    "iid_partition",
    "dirichlet_partition",
    "label_shard_partition",
    "ByteTokenizer",
    "DatasetUnavailable",
    "load_ag_news",
    "load_cifar10",
    "load_digits_real",
    "load_mnist",
]
