"""JAX version compatibility shims for the parallel layer.

``jax.shard_map`` became a top-level API (with the ``check_vma``
keyword) only in recent JAX; on 0.4.x the same transform lives at
``jax.experimental.shard_map.shard_map`` and spells the varying-
manifest check ``check_rep``. Every shard_map call site in this
package goes through :func:`shard_map` so the repo imports and runs on
both spellings — a bare ``from jax import shard_map`` breaks module
import (and with it test collection) on 0.4.37.
"""

from __future__ import annotations

import inspect

import jax
from jax import lax

_NATIVE = hasattr(jax, "shard_map")

if not _NATIVE:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    # Transitional 0.4.x/0.5.x releases grew ``check_vma`` (and with it
    # the varying-manifest checker) on the *experimental* entry point
    # before shard_map moved to the jax namespace. Detect it once at
    # import: with check_vma present the checker understands lax.pcast
    # manifests, so the caller's intent can pass through instead of the
    # blanket check_rep=False we need on genuinely old checkers.
    try:
        _EXPERIMENTAL_HAS_VMA = "check_vma" in inspect.signature(
            _experimental_shard_map
        ).parameters
    except (TypeError, ValueError):
        _EXPERIMENTAL_HAS_VMA = False
else:
    _EXPERIMENTAL_HAS_VMA = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    """``jax.shard_map`` when available, else the experimental one with
    ``check_vma`` translated to its old name ``check_rep``. Usable both
    directly and via ``functools.partial`` as a decorator."""
    if _NATIVE:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kwargs,
        )
    if _EXPERIMENTAL_HAS_VMA:
        # manifest-aware fallback: re-enable the replication checker
        # with the caller's setting instead of unconditionally off
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kwargs,
        )
    # check_rep is disabled on the legacy fallback path: that
    # replication checker predates lax.pcast, so code annotated for the
    # varying-manifest world (ring_attention's per-step lax.cond) trips
    # it with false "mismatched replication types" errors.
    return _experimental_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, **kwargs,
    )


def pcast_varying(x, axis_name: str):
    """``lax.pcast(x, axis, to="varying")`` on JAX versions with the
    varying-manifest API; identity on 0.4.x, whose replication checker
    has no per-value manifest to adjust."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return x
