"""Asynchronous federated learning (FedBuff-style buffered aggregation).

Everything else in the framework is round-synchronous — the reference's
only mode (a round ends when every started client reports, reference
manager.py:109-110). Real cross-device federations are asynchronous:
clients start and finish at different times, so an update is computed
against a *stale* anchor (the globals as of when its client started).
The standard server rule (FedBuff) is: keep ``concurrency`` clients in
flight, buffer completed updates, and as soon as ``buffer_size`` have
arrived apply their staleness-discounted average and bump the global
version.

TPU-first shape of the simulation: the ``buffer_size`` completions of a
server step train as ONE vmapped dispatch — ``vmap`` runs over clients
AND their per-client stale anchors (stacked ``[K, ...]`` params), so the
whole async step is a single XLA program; the host only runs the queue
bookkeeping. Staleness weighting uses the standard polynomial discount
``(1 + s)**(-alpha)``.

Under a clients mesh the stacked buffer axis shards exactly like a
synchronous wave (``shard_map`` over ``Mesh(('clients',))``, each device
training ``K/n_dev`` in-flight completions) — numerically identical to
the single-device path, tested leaf-for-leaf in
tests/test_fedbuff.py::test_mesh_fedbuff_matches_single_device. The
queue/staleness bookkeeping stays host-side Python by design: it is
O(concurrency) integer work per step, invariant to model size, and runs
concurrently with the device's dispatched training step.

Semantics are validated two ways (tests/test_fedbuff.py): with
``concurrency == buffer_size == C`` and all clients starting at the same
version, one async step is EXACTLY one synchronous FedAvg round
(weighted-delta form); and under genuine staleness the model still
reaches the demo coefficients while plain averaging of stale deltas with
no discount diverges more.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from baton_tpu.ops import aggregation as agg
from baton_tpu.parallel.compat import shard_map
from baton_tpu.parallel.engine import FedSim
from baton_tpu.parallel.mesh import (
    CLIENT_AXIS,
    client_sharding,
    replicated_sharding,
    require_clients_mesh,
)
from baton_tpu.parallel.partition import kernel_specs

Params = Any


@dataclasses.dataclass
class AsyncResult:
    params: Params
    version: int                 # server steps applied
    mean_staleness: float        # average staleness of applied updates
    loss_history: np.ndarray     # [n_steps] mean completed-client loss


class FedBuff:
    """Buffered asynchronous server loop over a :class:`FedSim`'s trainer.

    ``concurrency`` clients are always in flight; each server step
    completes the ``buffer_size`` longest-running ones, applies the
    staleness-discounted weighted mean of their DELTAS to the globals,
    and backfills the pool with fresh clients anchored at the new
    version. Client completion order is the queue order (deterministic);
    staleness emerges from the overlap, exactly as in the FedBuff model.
    """

    def __init__(
        self,
        sim: FedSim,
        buffer_size: int = 4,
        concurrency: int = 8,
        alpha: float = 0.5,
        server_lr: float = None,
    ):
        """``server_lr`` scales the applied mean delta (the FedBuff
        paper's global learning rate). Under overlap, consecutive buffer
        flushes re-apply movement computed from the SAME anchor — up to
        ``concurrency / buffer_size`` times — so the effective step
        multiplies by that factor and full-strength application can
        diverge where synchronous FedAvg is stable. The default
        ``buffer_size / concurrency`` cancels exactly that multiplicity;
        pass 1.0 to reproduce plain buffered averaging."""
        if buffer_size <= 0 or concurrency < buffer_size:
            raise ValueError(
                f"need concurrency >= buffer_size >= 1, got "
                f"{concurrency} < {buffer_size}"
            )
        if sim.aggregator[0] != "mean":
            raise ValueError(
                "FedBuff applies a staleness-weighted mean; robust "
                "aggregators are a synchronous-round feature"
            )
        if sim.server_optimizer is not None:
            raise ValueError(
                "FedBuff applies server_lr-scaled mean deltas directly; "
                "a FedOpt server optimizer would be silently ignored — "
                "configure the FedSim without one for async runs"
            )
        if sim.mesh is not None:
            # the buffer axis is already stacked [K, ...] (anchors, data,
            # rngs), so a clients mesh shards it exactly like the engine
            # shards a synchronous wave — each device trains K/n_dev of
            # the in-flight completions, host keeps only the queue
            # bookkeeping. Hybrid clients x model meshes are out: the
            # anchor pool holds FULL per-client params, which is the
            # thing a model-sharded base exists to avoid.
            require_clients_mesh(sim.mesh, sim.aggregator, "FedBuff")
            n_dev = int(sim.mesh.devices.size)
            if buffer_size % n_dev != 0:
                raise ValueError(
                    f"buffer_size ({buffer_size}) must be a multiple of "
                    f"the clients-mesh size ({n_dev}) so each server "
                    "step shards evenly — phantom-padding an async "
                    "buffer would skew the staleness discount"
                )
        self.sim = sim
        self.buffer_size = buffer_size
        self.concurrency = concurrency
        self.alpha = alpha
        self.server_lr = (
            server_lr if server_lr is not None
            else buffer_size / concurrency
        )

    # one vmapped dispatch for a whole buffer of completions: clients
    # AND their stale anchors are stacked along the leading axis. Each
    # client's OWN stale anchor is also its FedProx anchor (the globals
    # it started from), and frozen leaves (LoRA partition) broadcast
    # unstacked — mirroring the engine's wave kernel
    # (engine.py::_wave_params_raw).
    def _train_buffer_raw(self, anchors, data, n_samples, rngs, n_epochs,
                          frozen):
        trainer = self.sim.trainer
        with_anchor = trainer.regularizer is not None

        def one(p, d, n, r):
            new_p, _, losses = trainer.train(
                p, d, n, r, n_epochs, p if with_anchor else None, frozen
            )
            return new_p, losses

        return jax.vmap(one)(anchors, data, n_samples, rngs)

    def _train_buffer(self, anchors, data, n_samples, rngs, n_epochs,
                      frozen):
        mesh = self.sim.mesh
        if mesh is None:
            return self._train_buffer_raw(
                anchors, data, n_samples, rngs, n_epochs, frozen
            )
        # mesh path: shard the buffer axis, same math per shard. The
        # closure is cached per n_epochs — rebuilding it per step would
        # force an XLA recompile (mirrors engine._make_wave_sums_sharded).
        cache = getattr(self, "_sharded_cache", None)
        if cache is None:
            cache = self._sharded_cache = {}
        if n_epochs not in cache:
            def kernel(anchors, data, n_samples, rngs, frozen):
                return self._train_buffer_raw(
                    anchors, data, n_samples, rngs, n_epochs, frozen
                )

            in_specs, out_specs = kernel_specs("fedbuff.train")
            # donation decided no: the anchor stack is re-read
            # after the dispatch to form the staleness deltas
            cache[n_epochs] = jax.jit(shard_map(  # batonlint: allow[BTL011]
                kernel,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ))
        shard = client_sharding(mesh)
        anchors = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, shard), anchors
        )
        data = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, shard), data
        )
        n_samples = jax.device_put(n_samples, shard)
        rngs = jax.device_put(rngs, shard)
        if frozen is not None:
            frozen = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, replicated_sharding(mesh)),
                frozen,
            )
        return cache[n_epochs](anchors, data, n_samples, rngs, frozen)

    def run(
        self,
        params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: jax.Array,
        n_steps: int,
        n_epochs: int = 1,
    ) -> AsyncResult:
        """``data``/``n_samples`` in the engine's stacked ``[C, ...]``
        layout; clients are drawn round-robin from the cohort."""
        # honor the sim's trainable/frozen partition (LoRA): pool anchors
        # and deltas are trainable-only; frozen leaves broadcast into
        # every training dispatch and merge back at the end
        params, frozen = self.sim._split(params)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])

        # in-flight pool: (client_index, anchor_params, start_version)
        version = 0
        next_client = 0
        pool: Deque[Tuple[int, Params, int]] = deque()

        def fill() -> None:
            nonlocal next_client
            while len(pool) < self.concurrency:
                pool.append((next_client % c, params, version))
                next_client += 1

        fill()
        losses = []
        staleness_sum = 0.0
        n_applied = 0
        for step in range(n_steps):
            done = [pool.popleft() for _ in range(self.buffer_size)]
            idx = jnp.asarray([d[0] for d in done])
            anchors = agg.tree_stack([d[1] for d in done])
            stale = np.asarray([version - d[2] for d in done], np.float32)

            d_k = jax.tree_util.tree_map(
                lambda a: jnp.take(a, idx, axis=0), data
            )
            n_k = jnp.take(n_samples, idx, axis=0)
            rng, sub = jax.random.split(rng)
            r_k = jax.random.split(sub, self.buffer_size)

            trained, client_losses = self._train_buffer(
                anchors, d_k, n_k, r_k, n_epochs, frozen
            )
            # staleness-discounted, sample-weighted mean of DELTAS
            # applied to the CURRENT globals (not the stale anchors)
            deltas = jax.tree_util.tree_map(
                lambda t, a: t.astype(jnp.float32) - a.astype(jnp.float32),
                trained, anchors,
            )
            disc = (1.0 + stale) ** (-self.alpha)
            w = n_k.astype(jnp.float32) * jnp.asarray(disc)
            mean_delta = agg.weighted_tree_mean(deltas, w)
            lr_g = self.server_lr
            params = jax.tree_util.tree_map(
                lambda p, d: (p.astype(jnp.float32) + lr_g * d).astype(p.dtype),
                params, mean_delta,
            )
            version += 1
            staleness_sum += float(stale.sum())
            n_applied += len(done)
            losses.append(float(jnp.mean(client_losses[:, -1])))
            fill()

        if self.sim.partition is not None:
            params = self.sim.partition.merge(params, frozen)
        return AsyncResult(
            params=params,
            version=version,
            mean_staleness=staleness_sum / max(n_applied, 1),
            loss_history=np.asarray(losses),
        )
