"""Stateful clients — per-client optimizer state persisting across rounds.

Cross-DEVICE FedAvg resets each client's optimizer every round by design
(clients are anonymous and stateless — the engine's default, matching
the reference where a worker's ``train()`` builds a fresh optimizer each
call, reference demo.py:29-34). Cross-SILO federations are different:
the same few institutions participate every round, and letting each keep
its local Adam/momentum moments across rounds is the standard refinement
— local curvature information survives the round boundary.

TPU-first shape: the cohort's optimizer states live as ONE stacked
pytree ``[C, ...]`` (the same layout as client data and FedPer's
personal stack), so a round is a single vmapped dispatch of
``LocalTrainer.train_with_opt_state`` over (state, data, rng); trained
params aggregate with the sim's configured rule (mean / trimmed /
median) and a FedOpt server optimizer composes on top exactly as in the
synchronous engine. On a ``clients`` mesh the same body runs under
``shard_map`` with the state stack sharded over chips and psum FedAvg
over ICI (tested equal to the single-device rounds). The caller owns
the stack — checkpoint it next to the globals (the Checkpointer's
``extra`` slot) to resume a federation with its optimizer memory
intact.

Memory: C x optimizer state (≈ C x params for Adam) — the inherent cost
of statefulness, same scale as robust aggregation's stacked params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from baton_tpu.ops import aggregation as agg
from baton_tpu.parallel.compat import shard_map
from baton_tpu.parallel.engine import FedSim, _server_update

Params = Any


@dataclasses.dataclass
class StatefulRoundResult:
    params: Params
    opt_states: Params          # [C, ...] stacked, threads to next round
    loss_history: jax.Array     # [n_epochs] sample-weighted
    client_losses: jax.Array    # [C, n_epochs]
    server_opt_state: Any = None


class StatefulClients:
    """Synchronous rounds with persistent per-client optimizer state.

    Wraps a :class:`FedSim` (same model/trainer/aggregator config); use
    the sim's own ``run_round`` when clients should stay stateless.
    """

    def __init__(self, sim: FedSim):
        if sim.trainable_predicate is not None:
            raise ValueError(
                "StatefulClients threads full-param optimizer state; "
                "compose with LoRA by building the FedSim on the adapter "
                "pytree directly"
            )
        if sim.mesh is not None:
            from baton_tpu.parallel.mesh import require_clients_mesh

            require_clients_mesh(sim.mesh, sim.aggregator, "StatefulClients")
        self.sim = sim
        self._jit_cache: Dict[int, Any] = {}

    def init_opt_states(self, params: Params, n_clients: int) -> Params:
        """Stacked optimizer states, one per client, all initialized from
        the same global params."""
        opt0 = self.sim.trainer.optimizer.init(params)
        return jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(
                jnp.asarray(l), (n_clients,) + jnp.shape(l)
            ),
            opt0,
        )

    def _train_local(self, n_epochs: int):
        trainer = self.sim.trainer
        with_anchor = trainer.regularizer is not None

        def train_local(params, opt_states, data, n_samples, rngs):
            def one(os, d, n, r):
                new_p, new_os, losses = trainer.train_with_opt_state(
                    params, os, d, n, r, n_epochs,
                    params if with_anchor else None,
                )
                return new_p, new_os, losses

            return jax.vmap(one)(opt_states, data, n_samples, rngs)

        return train_local

    def _round_fn(self, n_epochs: int):
        if n_epochs not in self._jit_cache:
            self._jit_cache[n_epochs] = jax.jit(self._train_local(n_epochs))
        return self._jit_cache[n_epochs]

    def _round_fn_sharded(self, n_epochs: int):
        """Mesh path: the optimizer-state stack / data / rngs shard over
        the clients axis, globals replicated; aggregation is the
        engine's psum FedAvg over ICI (same layout rule as FedPer's
        sharded round)."""
        key = ("sharded", n_epochs)
        if key not in self._jit_cache:
            from baton_tpu.parallel.mesh import CLIENT_AXIS
            from baton_tpu.parallel.partition import kernel_specs

            train_local = self._train_local(n_epochs)

            def kernel(params, opt_states, data, n_samples, rngs):
                trained, new_os, closs = train_local(
                    params, opt_states, data, n_samples, rngs
                )
                w = n_samples.astype(jnp.float32)
                aggregate = agg.tree_cast_like(
                    agg.psum_weighted_mean(trained, w, CLIENT_AXIS), params
                )
                loss_hist = agg.psum_weighted_scalar_mean(closs, w,
                                                          CLIENT_AXIS)
                return aggregate, new_os, loss_hist, closs

            in_specs, out_specs = kernel_specs("stateful.round")
            # donation decided no: params is the retained anchor and
            # the optimizer-state stack is caller-threaded round state
            self._jit_cache[key] = jax.jit(shard_map(  # batonlint: allow[BTL011]
                kernel,
                mesh=self.sim.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ))
        return self._jit_cache[key]

    def run_round(
        self,
        params: Params,
        opt_states: Optional[Params],
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: jax.Array,
        n_epochs: int = 1,
        server_opt_state=None,
    ) -> StatefulRoundResult:
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        if opt_states is None:
            opt_states = self.init_opt_states(params, c)
        rngs = jax.random.split(rng, c)

        if self.sim.mesh is not None:
            from baton_tpu.parallel.mesh import (
                CLIENT_AXIS,
                shard_client_arrays,
            )
            from baton_tpu.parallel.personalization import _pad_stack

            from baton_tpu.ops.padding import round_up

            n_dev = int(self.sim.mesh.shape[CLIENT_AXIS])
            target = round_up(c, n_dev)
            # auto-pad with zero-weight phantoms like the engine's wave
            # path; phantom optimizer states are row-0 copies that the
            # all-masked training leaves untouched
            data_p, n_p, rngs_p = self.sim._pad_wave(
                data, n_samples, rngs, target
            )
            os_p = _pad_stack(opt_states, target - c)
            put = lambda t: shard_client_arrays(t, self.sim.mesh)
            aggregate, new_opt_states, loss_history, closs = (
                self._round_fn_sharded(n_epochs)(
                    params, put(os_p), put(data_p), put(n_p), put(rngs_p)
                )
            )
            new_opt_states = jax.tree_util.tree_map(
                lambda a: a[:c], new_opt_states
            )
            closs = closs[:c]
            if self.sim.server_optimizer is not None:
                if server_opt_state is None:
                    server_opt_state = self.sim.server_optimizer.init(params)
                new_params, server_opt_state = _server_update(
                    self.sim.server_optimizer, params, aggregate,
                    server_opt_state,
                )
            else:
                new_params = aggregate
            return StatefulRoundResult(
                params=new_params,
                opt_states=new_opt_states,
                loss_history=loss_history,
                client_losses=closs,
                server_opt_state=server_opt_state,
            )

        trained, new_opt_states, closs = self._round_fn(n_epochs)(
            params, opt_states, data, n_samples, rngs
        )

        w = n_samples.astype(jnp.float32)
        aggregate = agg.aggregate_stacked(
            self.sim.aggregator, trained, n_samples, params
        )

        if self.sim.server_optimizer is not None:
            if server_opt_state is None:
                server_opt_state = self.sim.server_optimizer.init(params)
            new_params, server_opt_state = _server_update(
                self.sim.server_optimizer, params, aggregate, server_opt_state
            )
        else:
            new_params = aggregate

        return StatefulRoundResult(
            params=new_params,
            opt_states=new_opt_states,
            loss_history=agg.weighted_scalar_mean(closs, w),
            client_losses=closs,
            server_opt_state=server_opt_state,
        )
