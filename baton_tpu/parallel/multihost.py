"""Multi-host meshes: DCN × ICI topology-aware device layout.

The reference's "distributed backend" is aiohttp over the open internet
(SURVEY §5); the TPU-native equivalent inside a pod is XLA collectives,
and across pods/hosts it is the same collectives routed over DCN. The
rule (per the standard TPU scaling recipe): put the axis with the
LEAST communication volume on DCN (outermost) and bandwidth-hungry
axes on ICI.

For federated simulation that mapping is natural: the ``clients`` axis
only communicates once per round (the FedAvg psum of one model-sized
tree), so it spans hosts over DCN; ``model``/``seq`` axes move
activations every layer, so they stay inside a host's ICI domain.

Single-process fallbacks keep everything testable on the virtual CPU
mesh (SURVEY §4d).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join the jax.distributed runtime (no-op when single-process).

    On TPU pods the arguments are auto-detected from the environment;
    pass them explicitly for manual bring-up. Returns this process's
    index. Replaces the reference's worker-side ``register_with_manager``
    bootstrap (worker.py:41-55) for the simulated-cohort scale-out path.
    """
    if num_processes is not None and num_processes <= 1:
        return 0
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Idempotent bring-up is fine; anything else (bad coordinator
        # address, timeout) must surface, not silently degrade to a
        # single-process run.
        if "already initialized" not in str(e).lower():
            raise
    return jax.process_index()


def make_hybrid_mesh(
    ici_axes: Sequence[Tuple[str, int]],
    dcn_axis: str = "clients",
) -> Mesh:
    """Mesh with ``dcn_axis`` spanning hosts and ``ici_axes`` spanning
    each host's chips.

    ``ici_axes`` are (name, size) with sizes multiplying to the
    per-host device count; the DCN axis size is the process count.
    Single-process: collapses to an ordinary device mesh with the same
    axis names (DCN axis = 1 or folded over local devices), so code is
    portable between the unit-test CPU mesh and a real pod.
    """
    n_proc = jax.process_count()
    local = jax.local_device_count()
    ici_names = [n for n, _ in ici_axes]
    ici_sizes = [s for _, s in ici_axes]
    ici_total = int(np.prod(ici_sizes)) if ici_sizes else 1
    if local % ici_total:
        raise ValueError(
            f"ICI axes {ici_axes} need {ici_total} devices/host but this "
            f"host has {local}"
        )
    dcn_size = n_proc * (local // ici_total)
    if n_proc > 1:
        try:
            devices = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=[local // ici_total] + ici_sizes,
                dcn_mesh_shape=[n_proc] + [1] * len(ici_sizes),
            )
            devices = devices.reshape((dcn_size,) + tuple(ici_sizes))
        except ValueError as e:
            # create_hybrid_device_mesh groups by the devices'
            # slice_index, which only multi-slice TPU topologies carry;
            # multi-process CPU (the two-process DCN test,
            # tests/test_multihost.py) and single-slice-per-host setups
            # land here. Grouping by process_index preserves the one
            # property the layout rule needs: each host's devices are
            # contiguous along the ICI axes, so only the dcn_axis
            # crosses processes. Any OTHER ValueError (a genuinely
            # untileable multi-slice layout) must surface, not silently
            # degrade to a topology-blind ring.
            if "slice" not in str(e).lower():
                raise
            import warnings

            warnings.warn(
                "create_hybrid_device_mesh found no slice topology "
                f"({e}); falling back to process-ordered device layout",
                stacklevel=2,
            )
            devs = sorted(jax.devices(),
                          key=lambda d: (d.process_index, d.id))
            devices = np.array(devs).reshape(
                (dcn_size,) + tuple(ici_sizes)
            )
    else:
        devices = mesh_utils.create_device_mesh(
            (dcn_size,) + tuple(ici_sizes)
        )
    return Mesh(devices, (dcn_axis, *ici_names))
