"""One declarative sharding layer for every parallel path.

Sharding decisions used to live in three places — the client-axis
helpers in ``parallel/mesh.py``, the Megatron-style per-leaf heuristics
in ``parallel/tensor_parallel.py``, and the hybrid clients×model code in
``parallel/engine.py``. This module unifies them behind one mechanism:
an ordered table of ``(regex, PartitionSpec)`` rules matched against the
param pytree's slash-joined key paths (core/partition.py:path_str),
producing ``NamedSharding``s for any mesh.

Matching is first-match-wins over the ordered rules; a rule may further
constrain the leaf rank (``ndim``) so e.g. stacked MoE expert weights
``[E, D, F]`` and a plain 2-D ``w_gate`` get different specs under the
same name. Scalar leaves are always replicated. Leaves no rule matches
fall back to replicated and bump a module-level warning counter so CI
tests can assert complete coverage. A spec whose sharded dims don't
divide the mesh axis sizes also falls back to replicated (correct, just
not sharded) — the same safety valve the old per-leaf heuristics had.

Every other ``parallel/`` module builds its specs from the helpers here
(``replicated_spec`` / ``client_spec`` / ``waved_client_spec`` /
``dim_spec``); ``tests/test_partition_rules.py`` enforces that no
``PartitionSpec`` is constructed ad hoc outside this file.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from baton_tpu.core.partition import path_str

logger = logging.getLogger(__name__)

Params = Any

# Mesh axis names — defined HERE (the root of the parallel/ import
# graph); mesh.py and tensor_parallel.py re-export them for back-compat.
CLIENT_AXIS = "clients"
MODEL_AXIS = "model"


# ---------------------------------------------------------------------------
# spec helpers — the only sanctioned PartitionSpec constructors
# ---------------------------------------------------------------------------

def replicated_spec() -> PartitionSpec:
    """Fully-replicated spec (the global model each round)."""
    return PartitionSpec()


def client_spec(axis: str = CLIENT_AXIS) -> PartitionSpec:
    """``[C, ...]`` stacked client arrays: dim 0 over the client axis."""
    return PartitionSpec(axis)


def waved_client_spec(axis: str = CLIENT_AXIS) -> PartitionSpec:
    """``[W, C, ...]`` wave-major client stacks (the fused round step's
    data layout): dim 1 over the client axis, waves replicated."""
    return PartitionSpec(None, axis)


def dim_spec(axis: str, dim: int, ndim: int) -> PartitionSpec:
    """Shard a single dimension ``dim`` of an ``ndim``-rank array over
    ``axis`` — e.g. ``dim_spec('seq', 2, 4)`` for [B, H, L, Dh]
    sequence-sharded attention blocks."""
    if not 0 <= dim < ndim:
        raise ValueError(f"dim {dim} out of range for ndim {ndim}")
    return PartitionSpec(*(axis if i == dim else None for i in range(ndim)))


def axes_spec(*axes: Optional[str]) -> PartitionSpec:
    """General escape hatch: PartitionSpec(*axes), so callers with a
    genuinely bespoke layout still route construction through here."""
    return PartitionSpec(*axes)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    """One ordered sharding rule.

    ``pattern`` is an uncompiled regex ``re.search``-ed against the
    slash-joined tree path; ``ndim``, when given, additionally requires
    the leaf rank to match (so stacked-expert and plain variants of the
    same leaf name can coexist in one table).
    """

    pattern: str
    spec: PartitionSpec
    ndim: Optional[int] = None

    def matches(self, path: str, leaf: Any) -> bool:
        if self.ndim is not None and getattr(leaf, "ndim", None) != self.ndim:
            return False
        return re.search(self.pattern, path) is not None


class _UnmatchedCounter:
    """Thread-safe counter of leaves that fell through every rule."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0

    def bump(self, rule_set: str, path: str) -> None:
        with self._lock:
            self._count += 1
        logger.warning(
            "partition: no rule in %r matched leaf %r; replicating", rule_set, path
        )

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def reset(self) -> None:
        with self._lock:
            self._count = 0


#: Module-level tally of unmatched leaves across every RuleSet — tests
#: assert it stays at zero for the shipped rule tables.
UNMATCHED = _UnmatchedCounter()


def unmatched_leaf_count() -> int:
    return UNMATCHED.count


def reset_unmatched_leaf_count() -> None:
    UNMATCHED.reset()


def _is_scalar(leaf: Any) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True
    n = 1
    for d in shape:
        n *= d
    return len(shape) == 0 or n == 1


def _divisible(leaf: Any, spec: PartitionSpec, mesh: Mesh) -> bool:
    """Can ``leaf`` actually be split per ``spec`` on ``mesh``? Each
    sharded dim must divide the product of its mesh axis sizes."""
    for dim, names in zip(leaf.shape, spec):
        if names is None:
            continue
        axes = names if isinstance(names, tuple) else (names,)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if dim % size:
            return False
    return True


@dataclasses.dataclass(frozen=True)
class RuleSet:
    """A named, ordered rule table — the declarative partition config.

    ``name`` is recorded in bench output (``partition_rule_set``) and in
    plan_probe's spec-equality report, so a perf record always names the
    sharding policy that produced it.
    """

    name: str
    rules: Tuple[Rule, ...]

    def spec_for(self, path: str, leaf: Any) -> PartitionSpec:
        """First-match-wins spec for one leaf. Scalars are always
        replicated; unmatched leaves replicate and bump ``UNMATCHED``."""
        if _is_scalar(leaf):
            return replicated_spec()
        for rule in self.rules:
            if rule.matches(path, leaf):
                return rule.spec
        UNMATCHED.bump(self.name, path)
        return replicated_spec()

    def leaf_sharding(self, path: str, leaf: Any, mesh: Mesh) -> NamedSharding:
        """NamedSharding for one leaf, with the divisibility fallback."""
        spec = self.spec_for(path, leaf)
        if spec != replicated_spec() and not _divisible(leaf, spec, mesh):
            spec = replicated_spec()
        return NamedSharding(mesh, spec)

    def tree_specs(self, params: Params) -> Params:
        """The PartitionSpec pytree for ``params`` (mesh-independent —
        no divisibility fallback applied)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            treedef, [self.spec_for(path_str(p), leaf) for p, leaf in flat]
        )

    def shardings(self, params: Params, mesh: Mesh) -> Params:
        """The NamedSharding pytree for ``params`` on ``mesh`` — usable
        as jit's ``in_shardings``/``out_shardings`` so updated params
        KEEP the layout across steps instead of decaying to replicated."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            treedef,
            [self.leaf_sharding(path_str(p), leaf, mesh) for p, leaf in flat],
        )

    def place(self, params: Params, mesh: Mesh) -> Params:
        """Device-put ``params`` onto ``mesh`` per the rules. Any jitted
        function consuming the result inherits the layout — GSPMD
        propagates it and inserts the collectives."""
        return jax.tree_util.tree_map(
            jax.device_put, params, self.shardings(params, mesh)
        )

    def describe(self, params: Params, mesh: Optional[Mesh] = None) -> Dict[str, str]:
        """{path: spec-string} — introspection and the plan_probe
        spec-equality report. With a mesh, the divisibility fallback is
        applied (what would actually be placed); without, the raw rule
        outcome."""
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        out: Dict[str, str] = {}
        for p, leaf in flat:
            path = path_str(p)
            if mesh is not None:
                out[path] = str(self.leaf_sharding(path, leaf, mesh).spec)
            else:
                out[path] = str(self.spec_for(path, leaf))
        return out


def match_partition_rules(
    rules: Iterable[Tuple[str, PartitionSpec]],
    params: Params,
    name: str = "ad-hoc",
) -> Params:
    """The SNIPPETS-idiom entry point: ordered ``(regex, spec)`` pairs →
    PartitionSpec pytree. Sugar for ``RuleSet(...).tree_specs(...)``."""
    rs = RuleSet(name, tuple(Rule(pat, spec) for pat, spec in rules))
    return rs.tree_specs(params)


# ---------------------------------------------------------------------------
# default rule tables per model family
# ---------------------------------------------------------------------------

def transformer_rules(axis: str = MODEL_AXIS) -> RuleSet:
    """Megatron-style table for the transformer zoo (Llama swiglu,
    BERT/ViT gelu MLP, MoE, and LoRA-wrapped variants).

    Rules are anchored on the FINAL path component (``(^|/)name$``) so
    they apply uniformly at any nesting depth — but NOT to LoRA adapter
    factors, whose paths end in ``.../a`` / ``.../b`` and correctly fall
    to the replicated catch-all (adapters are per-client state riding
    the clients axis, never the model axis).

    * stacked MoE experts ``[E, D, F]``: expert dim sharded;
    * column-parallel (output features): wq/wk/wv, w_gate/w_up, w1
      (+ bias b1), lm_head;
    * row-parallel (contraction dim, where GSPMD places the Megatron
      all-reduce): wo, w_down, w2;
    * vocab-sharded embedding rows: tok_emb;
    * everything else (norms, other biases, small heads): replicated.
    """
    return RuleSet(
        name=f"transformer-tp[{axis}]",
        rules=(
            Rule(r"(^|/)(w_gate|w_up|w_down)$", PartitionSpec(axis, None, None), ndim=3),
            Rule(r"(^|/)(wq|wk|wv|w_gate|w_up|w1|lm_head)$", PartitionSpec(None, axis), ndim=2),
            Rule(r"(^|/)(wo|w_down|w2|tok_emb)$", PartitionSpec(axis, None), ndim=2),
            Rule(r"(^|/)b1$", PartitionSpec(axis), ndim=1),
            Rule(r".*", replicated_spec()),
        ),
    )


def client_stacked_rules(axis: str = CLIENT_AXIS) -> RuleSet:
    """``[C, ...]`` per-client stacked state (params/opt-state/rngs):
    every leaf rides the client axis on dim 0."""
    return RuleSet(name=f"client-stacked[{axis}]", rules=(Rule(r".*", client_spec(axis)),))


def replicated_rules() -> RuleSet:
    """Everything replicated — the broadcast global model."""
    return RuleSet(name="replicated", rules=(Rule(r".*", replicated_spec()),))


#: The default rule tables, keyed by the name bench.py records.
DEFAULT_RULE_SETS: Dict[str, Callable[[], RuleSet]] = {
    "transformer-tp": transformer_rules,
    "client-stacked": client_stacked_rules,
    "replicated": replicated_rules,
}


# ---------------------------------------------------------------------------
# shard_map kernel layout table
# ---------------------------------------------------------------------------

def kernel_specs(
    name: str, axis: str = CLIENT_AXIS
) -> Tuple[Tuple[PartitionSpec, ...], Tuple[PartitionSpec, ...]]:
    """``(in_specs, out_specs)`` for every shard_map kernel in the
    algorithm paths — the one place the layouts live. The modules
    consume these verbatim (tests assert the table against the intended
    layouts, and the no-ad-hoc-PartitionSpec lint keeps construction
    out of the call sites), so a layout change is a one-line table edit
    that every path and test sees at once.

    The invariant across all kernels: per-client stacked inputs/outputs
    (data, n_samples, rngs, per-client params/opt/personal state,
    per-client losses) ride the client axis; broadcast global state
    (params, frozen leaves, shared halves) and psum-folded aggregates
    are replicated.
    """
    cli, rep = client_spec(axis), replicated_spec()
    table = {
        # (params, frozen, data, n, rngs) -> (psum, lsum, wsum, closs)
        "engine.wave_sums": ((rep, rep, cli, cli, cli),
                             (rep, rep, rep, cli)),
        # (params, frozen, data, n, rngs) -> (client_params, closs)
        "engine.wave_params": ((rep, rep, cli, cli, cli), (cli, cli)),
        # (params_stack, data, n, rngs, frozen) -> (client_params, closs)
        "fedbuff.train": ((cli, cli, cli, cli, rep), (cli, cli)),
        # (cluster_params, data, n, rngs)
        #   -> (new_cluster_params, assignments, closs)
        "clustered.round": ((rep, cli, cli, cli), (rep, cli, cli)),
        # (params, opt_states, data, n, rngs)
        #   -> (psums, new_opt_states, lsum_w_wsum, closs)
        "stateful.round": ((rep, cli, cli, cli, cli),
                           (rep, cli, rep, cli)),
        # (personal_state, shared, data, n, rngs)
        #   -> (new_pers, shared_agg, pers_mean, loss_hist, closs)
        "personalization.round": ((cli, rep, cli, cli, cli),
                                  (cli, rep, rep, rep, cli)),
    }
    return table[name]
