"""FedSim — the TPU-resident federated simulation engine.

This is the heart of the framework: the reference's
round = HTTP broadcast → N worker processes train → HTTP gather → Python
weighted sum (SURVEY §3.2) becomes

  round = replicate global params
        → ``vmap``-ped jitted local training over a client axis
        → sample-weighted psum/tensordot aggregation

with *zero* Python in the hot path. Three execution modes, all the same
math:

* **vmap** (single device): clients stacked on a leading axis.
* **shard_map** (mesh): the client axis sharded over a
  ``Mesh(('clients',))``; aggregation via ICI collectives
  (:func:`baton_tpu.ops.aggregation.psum_weighted_mean`).
* **waves**: when C clients × model size exceeds HBM, the cohort is
  processed in waves of ``wave_size``; each wave contributes weighted
  *sums* (params·w, losses·w, Σw) accumulated on device, with the divide
  at the end — numerically identical to one big FedAvg (the weighted
  mean is associative in its sums).

Server-side optimizers (FedOpt family) treat ``global − aggregate`` as a
pseudo-gradient fed to an optax transform — plain FedAvg is the identity
case (replaces the in-place assignment at reference manager.py:123-126).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding

from baton_tpu.core.model import FedModel
from baton_tpu.core.partition import PathPredicate, make_partition
from baton_tpu.core.training import LocalTrainer, make_local_trainer, make_evaluator
from baton_tpu.obs.compute import ComputeProbe
from baton_tpu.ops import aggregation as agg
from baton_tpu.ops.padding import round_up
from baton_tpu.parallel.compat import shard_map
from baton_tpu.parallel.mesh import CLIENT_AXIS, client_sharding, replicated_sharding
from baton_tpu.parallel.partition import (
    client_spec,
    kernel_specs,
    replicated_spec,
    waved_client_spec,
)
from baton_tpu.parallel.tensor_parallel import MODEL_AXIS, shard_params_tp

Params = Any


@dataclasses.dataclass
class RoundResult:
    """Outcome of one federated round (replaces the reference's
    ``update_manager.client_responses`` dict + manager-side aggregation)."""

    params: Params
    loss_history: jax.Array  # [n_epochs] sample-weighted across clients
    client_losses: Optional[jax.Array]  # [C, n_epochs]
    n_samples_total: jax.Array
    server_opt_state: Any = None


def client_eval_sums(model: FedModel, params, d, n, r):
    """One client's evaluation sums: masked loss sum, valid count, and —
    for rank-1 integer labels — correct-prediction sum. The single
    definition of the accuracy-eligibility rule, shared by FedSim's
    federated eval and FedPer's personalized eval
    (parallel/personalization.py)."""
    losses = model.per_example_loss(params, d, r)
    mask = (jnp.arange(losses.shape[0]) < n).astype(jnp.float32)
    out = {
        "loss_sum": jnp.sum(losses.astype(jnp.float32) * mask),
        "n": mask.sum(),
    }
    y = d.get("y")
    # accuracy only for rank-1 class labels (y [B] matching the
    # per-example losses); sequence targets (LM: y [B, L]) have no
    # single-label accuracy and would shape-mismatch the mask
    if (y is not None and jnp.issubdtype(y.dtype, jnp.integer)
            and y.ndim == losses.ndim):
        # model.apply here repeats per_example_loss's forward
        # structurally — XLA CSEs the shared subgraph (measured:
        # +2.6% flops vs loss-only, not 2x), so one jit is enough
        logits = model.apply(params, d, r)
        correct = (jnp.argmax(logits, axis=-1) == y).astype(jnp.float32)
        out["correct_sum"] = jnp.sum(correct * mask)
    return out


class FedSim:
    """Simulated-clients federated training on one device or a mesh.

    Data layout: ``data`` is a dict of ``[C, capacity, ...]`` arrays
    (see :func:`baton_tpu.ops.padding.stack_client_datasets`) and
    ``n_samples`` is ``[C]`` — client ``c``'s true row count, which is
    also its FedAvg weight (reference manager.py:119-126 semantics).
    """

    def __init__(
        self,
        model: FedModel,
        optimizer: Optional[optax.GradientTransformation] = None,
        batch_size: int = 32,
        learning_rate: float = 1e-3,
        server_optimizer: Optional[optax.GradientTransformation] = None,
        mesh: Optional[Mesh] = None,
        regularizer=None,
        trainable: Optional[PathPredicate] = None,
        dp=None,
        aggregator: str = "mean",
    ):
        """``aggregator`` selects the round combine rule:

        * ``"mean"`` (default) — sample-weighted FedAvg, the reference
          rule (manager.py:119-126); streams as per-wave weighted sums,
          so memory is O(model), not O(clients x model).
        * ``"trimmed:<ratio>"`` — coordinate-wise trimmed mean,
          ``"median"`` — coordinate-wise median (ops/aggregation.py):
          Byzantine-robust rules that need every client's params
          materialized ([C, model] HBM — the price of robustness) and
          are unweighted (standard formulations; a poisoned client
          could otherwise buy influence by claiming a huge n_samples).
          Zero-sample clients are excluded before the combine.
        """
        self.model = model
        self.aggregator = agg.parse_aggregator(aggregator)
        self.trainer: LocalTrainer = make_local_trainer(
            model,
            optimizer=optimizer,
            batch_size=batch_size,
            learning_rate=learning_rate,
            regularizer=regularizer,
            dp=dp,
        )
        self.server_optimizer = server_optimizer
        self.mesh = mesh
        self.evaluate = make_evaluator(model)
        # ``trainable(path, leaf) -> bool`` restricts training/aggregation
        # to a sub-pytree (LoRA adapters); frozen leaves are replicated
        # once, never per-client. Partition built lazily from the first
        # params seen (structure unknown until then).
        self.trainable_predicate = trainable
        self.partition = None
        # compute-plane probe: run_round leaves its per-round compute
        # record (MFU/compile/HBM, null-with-reason) in ``last_compute``
        # for the caller (the manager's simulated-cohort path ships it
        # into the round's SLO record). Costs one scalar sync per round.
        self.compute_probe = ComputeProbe(model=model)
        self.last_compute: Optional[dict] = None

    def _ensure_partition(self, params):
        if self.trainable_predicate is None or self.partition is not None:
            return
        self.partition = make_partition(params, self.trainable_predicate)
        self.trainer = dataclasses.replace(self.trainer, partition=self.partition)

    def _split(self, params):
        """(trainable, frozen) — identity when no partition is configured."""
        if self.trainable_predicate is None:
            return params, None
        self._ensure_partition(params)
        return self.partition.split(params)

    # ------------------------------------------------------------------
    @property
    def is_hybrid(self) -> bool:
        """True for a ``('clients', 'model')``-style hybrid mesh: the
        frozen base rides tensor-parallel shardings on the ``model`` axis
        while per-client work spreads over ``clients`` (BASELINE config 4
        — a Llama-8B base physically cannot replicate per chip)."""
        return self.mesh is not None and MODEL_AXIS in self.mesh.axis_names

    @property
    def partition_rule_set(self) -> str:
        """Name of the :data:`~baton_tpu.parallel.partition.DEFAULT_RULE_SETS`
        table governing this sim's placement — recorded in bench output."""
        if self.is_hybrid:
            return "transformer-tp"
        if self.mesh is not None:
            return "client-stacked"
        return "replicated"

    def _clients_per_wave_unit(self) -> int:
        """Wave sizes must be a multiple of the client-axis extent."""
        if self.mesh is None:
            return 1
        if self.is_hybrid:
            return int(self.mesh.shape[CLIENT_AXIS])
        return int(self.mesh.devices.size)

    def _place_hybrid(self, params, frozen):
        """GSPMD placement for the hybrid mesh: trainable globals
        replicated, frozen base tensor-parallel over ``model``. Data is
        placed per-wave (client_sharding). XLA's GSPMD partitioner then
        derives the whole round program — per-client compute partitioned
        over ``clients``, every frozen-base matmul Megatron-sharded over
        ``model`` — with no shard_map or manual collectives."""
        params = jax.device_put(params, replicated_sharding(self.mesh))
        if frozen is not None:
            # frozen is a flat leaf list (partition.split); shard each
            # leaf by its ORIGINAL tree path so the Megatron name rules
            # (wq/wo/w_gate/…) still apply
            from baton_tpu.parallel.tensor_parallel import (
                leaf_tp_sharding,
            )

            paths = self.partition.frozen_paths if self.partition else None
            if paths and len(paths) == len(frozen):
                frozen = [
                    jax.device_put(
                        leaf, leaf_tp_sharding(path, leaf, self.mesh)
                    )
                    for path, leaf in zip(paths, frozen)
                ]
            else:
                frozen = shard_params_tp(frozen, self.mesh)
        return params, frozen

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> Params:
        return self.model.init(rng)

    def init_server_opt_state(self, params: Params):
        if self.server_optimizer is None:
            return None
        trainable, _ = self._split(params)
        return self.server_optimizer.init(trainable)

    # ------------------------------------------------------------------
    # wave kernels: return (Σ w·params, Σ w·losses, Σ w, per-client losses)
    def _wave_sums_raw(self, params, frozen, data, n_samples, rngs, n_epochs):
        anchor = params if self.trainer.regularizer is not None else None

        def one_client(d, n, r):
            p, _, losses = self.trainer.train(
                params, d, n, r, n_epochs, anchor, frozen
            )
            return p, losses

        client_params, client_losses = jax.vmap(one_client)(data, n_samples, rngs)
        w = n_samples.astype(jnp.float32)
        psum = agg.weighted_tree_sum(client_params, w)
        lsum = jnp.tensordot(w, client_losses.astype(jnp.float32), axes=(0, 0))
        return psum, lsum, jnp.sum(w), client_losses

    # HBM note on donation: wave INPUTS are deliberately not donated.
    # `params` is reused by every wave of the round (and as the FedProx
    # anchor), and the per-wave data/rng slices alias the caller's arrays
    # when a round fits in one wave (jnp identity slices return the same
    # buffer), so donating them would invalidate data the caller reuses
    # across rounds. Donation lives where it is safe and large: the
    # fused round runner donates params+opt state by default
    # (run_rounds_fused, donate_argnums), the wave loop donates its
    # model-sized psum accumulator (_acc_tree_add), and
    # LocalTrainer.train_with_opt_state donates the per-client optimizer
    # state (training.py) — the buffers that would otherwise be
    # double-buffered per round.
    # donation decided no: params is the round's retained anchor,
    # re-read by every wave (and by FedProx as the prox center)
    @partial(jax.jit, static_argnums=(0, 6))  # batonlint: allow[BTL011]
    def _wave_sums_vmap(self, params, frozen, data, n_samples, rngs, n_epochs):
        return self._wave_sums_raw(params, frozen, data, n_samples, rngs, n_epochs)

    # robust-aggregation wave kernel: returns every client's trained
    # params ([C_wave, ...] stacked) instead of streaming weighted sums —
    # trimmed mean/median are order statistics and cannot be computed
    # from sums (engine __init__ docstring on the memory trade)
    def _wave_params_raw(self, params, frozen, data, n_samples, rngs, n_epochs):
        anchor = params if self.trainer.regularizer is not None else None

        def one_client(d, n, r):
            p, _, losses = self.trainer.train(
                params, d, n, r, n_epochs, anchor, frozen
            )
            return p, losses

        return jax.vmap(one_client)(data, n_samples, rngs)

    # donation decided no: same retained-anchor contract as
    # _wave_sums_vmap
    @partial(jax.jit, static_argnums=(0, 6))  # batonlint: allow[BTL011]
    def _wave_params_vmap(self, params, frozen, data, n_samples, rngs, n_epochs):
        return self._wave_params_raw(params, frozen, data, n_samples, rngs,
                                     n_epochs)

    def _make_wave_params_sharded(self, n_epochs: int):
        cache = getattr(self, "_sharded_params_cache", None)
        if cache is None:
            cache = self._sharded_params_cache = {}
        if n_epochs not in cache:
            mesh = self.mesh

            def kernel(params, frozen, data, n_samples, rngs):
                return self._wave_params_raw(
                    params, frozen, data, n_samples, rngs, n_epochs
                )

            in_specs, out_specs = kernel_specs("engine.wave_params")
            # donation decided no: params is the caller-retained
            # anchor, re-read across waves
            cache[n_epochs] = jax.jit(shard_map(  # batonlint: allow[BTL011]
                kernel,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ))
        return cache[n_epochs]

    def _make_wave_sums_sharded(self, n_epochs: int, raw: bool = False):
        # Cache per n_epochs: rebuilding the shard_map closure every round
        # would hand jit a fresh function and force an XLA recompile.
        cache = getattr(self, "_sharded_cache", None)
        if cache is None:
            cache = self._sharded_cache = {}
        if n_epochs not in cache:
            mesh = self.mesh

            def kernel(params, frozen, data, n_samples, rngs):
                # per-shard wave math is _wave_sums_raw verbatim; only the
                # three ICI reductions are mesh-specific
                local_psum, local_lsum, local_w, client_losses = (
                    self._wave_sums_raw(
                        params, frozen, data, n_samples, rngs, n_epochs
                    )
                )
                psum = jax.lax.psum(local_psum, CLIENT_AXIS)
                lsum = jax.lax.psum(local_lsum, CLIENT_AXIS)
                wtot = jax.lax.psum(local_w, CLIENT_AXIS)
                return psum, lsum, wtot, client_losses

            in_specs, out_specs = kernel_specs("engine.wave_sums")
            sharded = shard_map(
                kernel,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )
            # donation decided no: params is the caller-retained
            # anchor, re-read across waves
            cache[n_epochs] = (
                sharded,
                jax.jit(sharded),  # batonlint: allow[BTL011]
            )
        sharded, jitted = cache[n_epochs]
        return sharded if raw else jitted

    # ------------------------------------------------------------------
    def _pad_wave(self, data, n_samples, rngs, target: int):
        """Pad a short/unaligned wave with zero-weight phantom clients —
        they train on all-masked data (exactly-zero grads) and carry
        FedAvg weight 0, so they cannot perturb the aggregate."""
        c = n_samples.shape[0]
        if c == target:
            return data, n_samples, rngs
        pad = target - c

        def pad_leaf(a):
            return jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
            )

        data = jax.tree_util.tree_map(pad_leaf, data)
        n_samples = jnp.concatenate(
            [n_samples, jnp.zeros((pad,), n_samples.dtype)]
        )
        # Phantom clients have weight 0, so their rng only needs a valid
        # shape — repeat the first key rather than slicing (a short wave
        # can have fewer real clients than the pad amount).
        rngs = jnp.concatenate(
            [rngs, jnp.repeat(rngs[:1], pad, axis=0)], axis=0
        )
        return data, n_samples, rngs

    # ------------------------------------------------------------------
    def auto_wave_size(self, params, data, n_samples, key=None,
                       n_epochs: int = 1,
                       budget_gb: Optional[float] = None) -> Optional[int]:
        """Largest wave size whose XLA static memory plan fits the
        device budget — the benchmark-side OOM guard productized: on a
        tunneled/shared chip an out-of-memory execution can take the
        accelerator down for hours, so size waves from the compiler's
        own plan instead of trial-and-error. Compiles wave kernels
        (cached persistently) but never executes them.

        Returns ``None`` when the full cohort fits as one wave, else
        the halved-until-it-fits wave size (a multiple of the wave
        unit). Raises ``RuntimeError`` when no wave down to one wave
        unit fits, and ``NotImplementedError`` for robust aggregators
        (their per-client-params-stacking kernel has a different, much
        larger footprint than the weighted-sums kernel this probes —
        sizing from the wrong kernel would admit waves that OOM; set
        wave_size explicitly there). When the backend surfaces no
        memory analysis (some CPU configs), the full cohort is assumed
        to fit — matching the pre-auto behavior. ``budget_gb``
        overrides the per-device-kind plan budget
        (profiling.hbm_budget_gb, conservative tier).

        On a clients mesh the probe lowers the PER-SHARD program (each
        device executes wave/n_dev clients under shard_map), so the
        plan is compared against one device's budget."""
        from baton_tpu.utils.profiling import (
            fedsim_wave_plan_gb,
            hbm_budget_gb,
        )

        if self.aggregator[0] != "mean":
            raise NotImplementedError(
                "auto_wave_size probes the weighted-sums wave kernel; "
                f"aggregator={self.aggregator[0]!r} executes the "
                "per-client-params-stacking kernel with a different "
                "footprint — pass an explicit wave_size")
        if budget_gb is None:
            budget_gb = hbm_budget_gb(jax.devices()[0])
        if key is None:
            key = jax.random.key(0)
        n_samples = jnp.asarray(n_samples)
        unit = self._clients_per_wave_unit()
        n_dev = unit  # clients mesh: one wave unit = one client per device
        w = round_up(int(n_samples.shape[0]), unit)
        while True:
            # per-device footprint: each device runs a wave/n_dev-client
            # program under shard_map
            plan = fedsim_wave_plan_gb(
                self, params, data, n_samples, key,
                wave_size=max(1, w // n_dev), n_epochs=n_epochs)
            if plan is None or plan <= budget_gb:
                break
            if w <= unit:
                raise RuntimeError(
                    f"no wave size down to {unit} fits the "
                    f"{budget_gb:.1f} GiB plan budget (smallest plan "
                    f"{plan:.1f} GiB) — shrink the per-client batch or "
                    "dataset instead of risking an OOM")
            w = round_up(max(unit, w // 2), unit)
        full = round_up(int(n_samples.shape[0]), unit)
        return None if w >= full else w

    def run_round(
        self,
        params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: jax.Array,
        n_epochs: int = 1,
        wave_size=None,
        server_opt_state=None,
        client_indices: Optional[np.ndarray] = None,
        collect_client_losses: bool = True,
        progress_fn=None,
    ) -> RoundResult:
        """Run one federated round; returns the new global params.

        ``client_indices`` selects a cohort (client sampling — the
        simulated analogue of only some registered clients acking a
        round, reference manager.py:87-92).

        ``progress_fn(waves_done, n_waves)`` is the simulated-cohort
        analogue of the worker's per-epoch hook (core/training.py):
        called on the host after each wave's device work completes.
        Costs a per-wave sync (blocks on the wave's loss scalar), so the
        host stops dispatching ahead of the device — leave unset for
        maximum-throughput runs, set it for long rounds that need
        mid-round visibility (reference utils.py:70-91 streamed
        progress; a multi-wave round is otherwise a black box).

        ``wave_size="auto"`` sizes waves from XLA's static memory plan
        (:meth:`auto_wave_size`); the decision is cached per cohort
        shape, so repeated rounds pay the plan compiles once.
        """
        orig_params = params
        params, frozen = self._split(params)
        n_samples = jnp.asarray(n_samples)
        if client_indices is not None:
            idx = jnp.asarray(client_indices)
            data = jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), data)
            n_samples = jnp.take(n_samples, idx, axis=0)
        c = int(n_samples.shape[0])
        rngs = jax.random.split(rng, c)

        n_dev = self._clients_per_wave_unit()
        if wave_size == "auto":
            cache_key = (
                c, n_epochs,
                tuple(sorted((k, v.shape, str(v.dtype))
                             for k, v in data.items())),
            )
            cache = getattr(self, "_auto_wave_cache", None)
            if cache is None:
                cache = self._auto_wave_cache = {}
            if cache_key not in cache:
                cache[cache_key] = self.auto_wave_size(
                    orig_params, data, n_samples, n_epochs=n_epochs)
            wave_size = cache[cache_key]
        if wave_size is None:
            wave_size = round_up(c, n_dev)
        else:
            wave_size = round_up(wave_size, n_dev)

        robust = self.aggregator[0] != "mean"
        if robust and self.is_hybrid:
            raise NotImplementedError(
                "robust aggregators need per-client params stacked along "
                "the client axis; the hybrid clients x model mesh shards "
                "params over 'model' — run robust rounds on a pure "
                "clients mesh"
            )
        if self.is_hybrid:
            # hybrid clients×model mesh: plain jit + GSPMD (see
            # _place_hybrid) — shard_map would force manual TP collectives
            params, frozen = self._place_hybrid(params, frozen)
            call = lambda d, n, r: self._wave_sums_vmap(
                params, frozen, d, n, r, n_epochs
            )
            in_shard = client_sharding(self.mesh)
        elif self.mesh is not None:
            if robust:
                wave_p = self._make_wave_params_sharded(n_epochs)
                call_p = lambda d, n, r: wave_p(params, frozen, d, n, r)
            else:
                wave_fn = self._make_wave_sums_sharded(n_epochs)
                call = lambda d, n, r: wave_fn(params, frozen, d, n, r)
            in_shard = client_sharding(self.mesh)
        else:
            if robust:
                call_p = lambda d, n, r: self._wave_params_vmap(
                    params, frozen, d, n, r, n_epochs
                )
            else:
                call = lambda d, n, r: self._wave_sums_vmap(
                    params, frozen, d, n, r, n_epochs
                )
            in_shard = None

        psum_acc = None
        lsum_acc = None
        w_acc = None
        stacked_parts = [] if robust else None
        per_client = [] if collect_client_losses else None
        t_waves0 = time.perf_counter()
        for start in range(0, c, wave_size):
            stop = min(start + wave_size, c)
            d = jax.tree_util.tree_map(lambda a: a[start:stop], data)
            n = n_samples[start:stop]
            r = rngs[start:stop]
            d, n, r = self._pad_wave(d, n, r, wave_size)
            if in_shard is not None:
                d = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, in_shard), d
                )
                n = jax.device_put(n, in_shard)
                r = jax.device_put(r, in_shard)
            if robust:
                cp, closs = call_p(d, n, r)
                real = stop - start
                stacked_parts.append(
                    jax.tree_util.tree_map(lambda a: a[:real], cp)
                )
                w_wave = n[:real].astype(jnp.float32)
                lsum = jnp.tensordot(w_wave,
                                     closs[:real].astype(jnp.float32),
                                     axes=(0, 0))
                wtot = jnp.sum(w_wave)
            else:
                psum, lsum, wtot, closs = call(d, n, r)
                psum_acc = (
                    psum if psum_acc is None else _acc_tree_add(psum_acc, psum)
                )
            lsum_acc = lsum if lsum_acc is None else lsum_acc + lsum
            w_acc = wtot if w_acc is None else w_acc + wtot
            if per_client is not None:
                per_client.append(closs[: stop - start])
            if progress_fn is not None:
                jax.block_until_ready(lsum)
                progress_fn(start // wave_size + 1, -(-c // wave_size))

        # --- compute record (obs/compute.py) ------------------------------
        # One scalar sync on the loss sum closes the timed window over
        # the wave loop (compile included on a cache miss — the tracker's
        # shape signature says whether this shape compiled). Guarded: a
        # probe failure must never fail training.
        try:
            jax.block_until_ready(lsum_acc)
            train_s = time.perf_counter() - t_waves0
            capacity = next(
                (int(a.shape[1]) for a in data.values()
                 if getattr(a, "ndim", 0) >= 2), 1)
            bsz = max(1, int(self.trainer.batch_size))
            sig = (c, int(wave_size), int(n_epochs), robust,
                   tuple(sorted((k, tuple(v.shape), str(v.dtype))
                                for k, v in data.items())))
            self.last_compute = self.compute_probe.record_round(
                key="run_round",
                signature=sig,
                train_s=train_s,
                n_samples=float(np.asarray(n_samples).sum()),
                n_epochs=n_epochs,
                steps=c * n_epochs * -(-capacity // bsz),
            )
        except Exception:
            self.last_compute = None

        denom = jnp.maximum(w_acc, 1e-9)
        if robust:
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *stacked_parts
            )
            aggregate = agg.aggregate_stacked(
                self.aggregator, stacked, n_samples, params
            )
        else:
            aggregate = jax.tree_util.tree_map(
                lambda s, ref: (s / denom).astype(ref.dtype), psum_acc, params
            )
        if self.is_hybrid:
            # GSPMD is free to leave the trainable aggregate
            # model-sharded (it flows out of matmuls against the TP
            # base), but the global state is logically replicated —
            # pin it back to the partition layer's replicated rule so
            # round outputs carry the same layout contract as inputs
            aggregate = jax.device_put(
                aggregate, replicated_sharding(self.mesh)
            )
        loss_history = lsum_acc / denom

        if self.server_optimizer is not None:
            if server_opt_state is None:
                server_opt_state = self.server_optimizer.init(params)
            new_params, server_opt_state = _server_update(
                self.server_optimizer, params, aggregate, server_opt_state
            )
        else:
            new_params = aggregate

        if self.partition is not None:
            new_params = self.partition.merge(new_params, frozen)

        return RoundResult(
            params=new_params,
            loss_history=loss_history,
            client_losses=jnp.concatenate(per_client, axis=0)
            if per_client
            else None,
            n_samples_total=w_acc,
            server_opt_state=server_opt_state,
        )

    # ------------------------------------------------------------------
    # federated evaluation: sample-weighted mean loss/accuracy over the
    # client axis — the eval-side analogue of the FedAvg weighting
    # donation decided no: evaluation never owns its inputs
    @partial(jax.jit, static_argnums=(0,))  # batonlint: allow[BTL011]
    def _eval_sums_vmap(self, params, data, n_samples, rngs):
        def one(d, n, r):
            return client_eval_sums(self.model, params, d, n, r)

        sums = jax.vmap(one)(data, n_samples, rngs)
        return jax.tree_util.tree_map(jnp.sum, sums)

    def evaluate_round(
        self,
        params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: Optional[jax.Array] = None,
        wave_size: Optional[int] = None,
    ) -> Dict[str, float]:
        """Evaluate global ``params`` on every client's local data
        (``[C, capacity, ...]`` layout) and return the example-weighted
        federation-wide ``{"loss": …, "accuracy": …}``.

        Memory scales like training's: ``wave_size`` chunks the client
        axis (host-accumulated sums — exact, the mean is associative),
        and under a mesh each wave's inputs are client-sharded so the
        vmapped forward runs shard-wise via GSPMD. Zero-sample phantom
        rows used for padding carry mask 0 and contribute nothing.
        """
        if rng is None:
            rng = jax.random.key(0)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        rngs = jax.random.split(rng, c)
        n_dev = self._clients_per_wave_unit()
        wave = round_up(wave_size if wave_size is not None else c, n_dev)
        in_shard = client_sharding(self.mesh) if self.mesh is not None else None

        totals: Dict[str, float] = {}
        for start in range(0, c, wave):
            stop = min(start + wave, c)
            d = jax.tree_util.tree_map(lambda a: a[start:stop], data)
            n = n_samples[start:stop]
            r = rngs[start:stop]
            d, n, r = self._pad_wave(d, n, r, wave)
            if in_shard is not None:
                d = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, in_shard), d
                )
                n = jax.device_put(n, in_shard)
                r = jax.device_put(r, in_shard)
            sums = self._eval_sums_vmap(params, d, n, r)
            for k, v in sums.items():
                totals[k] = totals.get(k, 0.0) + float(v)

        denom = max(totals.get("n", 0.0), 1.0)
        out = {"loss": totals.get("loss_sum", 0.0) / denom, "n": denom}
        if "correct_sum" in totals:
            out["accuracy"] = totals["correct_sum"] / denom
        return out

    # donation decided no: evaluation never owns its inputs
    @partial(jax.jit, static_argnums=(0,))  # batonlint: allow[BTL011]
    def _eval_sums_per_client(self, params, data, n_samples, rngs):
        def one(d, n, r):
            return client_eval_sums(self.model, params, d, n, r)

        return jax.vmap(one)(data, n_samples, rngs)  # [C]-leaved sums

    def evaluate_clients(
        self,
        params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: Optional[jax.Array] = None,
        wave_size: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Per-client evaluation + a fairness summary.

        The federation-wide mean (:meth:`evaluate_round`) hides exactly
        what non-IID federations care about: how unevenly the global
        model serves individual clients. Returns ``per_client`` arrays
        (loss, accuracy when defined, n — NaN for zero-sample clients)
        and a ``fairness`` block with mean/std plus direction-aware tail
        stats — ``worst`` and ``worst_decile`` are min/p10 for accuracy
        but max/p90 for loss, so they always describe the struggling
        clients. Waved and mesh-sharded like :meth:`evaluate_round`.
        """
        if rng is None:
            rng = jax.random.key(0)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        rngs = jax.random.split(rng, c)
        n_dev = self._clients_per_wave_unit()
        wave = round_up(wave_size if wave_size is not None else c, n_dev)
        in_shard = client_sharding(self.mesh) if self.mesh is not None else None

        parts = []
        for start in range(0, c, wave):
            stop = min(start + wave, c)
            d = jax.tree_util.tree_map(lambda a: a[start:stop], data)
            n = n_samples[start:stop]
            r = rngs[start:stop]
            d, n, r = self._pad_wave(d, n, r, wave)
            if in_shard is not None:
                # same client-sharded placement as evaluate_round: the
                # vmapped forward partitions over the mesh via GSPMD
                d = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, in_shard), d
                )
                n = jax.device_put(n, in_shard)
                r = jax.device_put(r, in_shard)
            sums = self._eval_sums_per_client(params, d, n, r)
            parts.append(jax.tree_util.tree_map(
                lambda a: np.asarray(a[: stop - start]), sums
            ))
        sums = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *parts
        )

        n_arr = sums["n"]
        valid = n_arr > 0
        denom = np.where(valid, n_arr, 1.0)
        per_client: Dict[str, Any] = {
            "loss": np.where(valid, sums["loss_sum"] / denom, np.nan),
            "n": n_arr,
        }
        metric = "loss"
        if "correct_sum" in sums:
            per_client["accuracy"] = np.where(
                valid, sums["correct_sum"] / denom, np.nan
            )
            metric = "accuracy"
        vals = per_client[metric][valid]
        # direction-aware tail: "worst" must mean the struggling clients
        # whichever the metric — min/p10 for accuracy, max/p90 for loss
        higher_is_better = metric == "accuracy"
        if vals.size:
            worst = float(np.min(vals) if higher_is_better else np.max(vals))
            worst_decile = float(
                np.percentile(vals, 10 if higher_is_better else 90)
            )
        else:
            worst = worst_decile = float("nan")
        fairness = {
            "metric": metric,
            "mean": float(np.mean(vals)) if vals.size else float("nan"),
            "std": float(np.std(vals)) if vals.size else float("nan"),
            "worst": worst,
            "worst_decile": worst_decile,
            "n_clients": int(valid.sum()),
        }
        return {"per_client": per_client, "fairness": fairness}

    # ------------------------------------------------------------------
    def run_rounds(
        self,
        params: Params,
        data,
        n_samples,
        rng: jax.Array,
        n_rounds: int,
        n_epochs: int = 1,
        checkpointer=None,
        checkpoint_every: int = 1,
        return_server_opt_state: bool = False,
        **kw,
    ):
        """Convenience loop over rounds; returns (params, loss_history list)
        — plus the final FedOpt server optimizer state when
        ``return_server_opt_state`` is set, so chained calls continue the
        server optimizer instead of silently resetting its moments.

        With a :class:`baton_tpu.utils.checkpoint.Checkpointer` the loop
        saves params/server-opt-state/history every ``checkpoint_every``
        rounds and resumes from the latest step on restart. Per-round
        rngs come from ``fold_in(rng, round_idx)`` so a resumed run
        replays the identical randomness it would have had uninterrupted.
        """
        history = []
        server_opt_state = kw.pop("server_opt_state", None)
        start = 0
        if checkpointer is not None:
            restored = checkpointer.restore(
                params,
                server_opt_template=self.init_server_opt_state(params),
            )
            if restored is not None:
                params = restored.params
                server_opt_state = restored.server_opt_state
                history = list(restored.meta.get("loss_history", []))
                start = restored.step
        for i in range(start, n_rounds):
            res = self.run_round(
                params,
                data,
                n_samples,
                jax.random.fold_in(rng, i),
                n_epochs=n_epochs,
                server_opt_state=server_opt_state,
                **kw,
            )
            params = res.params
            server_opt_state = res.server_opt_state
            history.extend(np.asarray(res.loss_history).tolist())
            if checkpointer is not None and (i + 1) % checkpoint_every == 0:
                # history items are already Python floats (np tolist)
                checkpointer.save(
                    i + 1,
                    params,
                    server_opt_state=server_opt_state,
                    meta={"loss_history": history},
                )
        if return_server_opt_state:
            return params, history, server_opt_state
        return params, history


    # ------------------------------------------------------------------
    # fused rounds: the whole multi-round federated loop as ONE compiled
    # XLA program — lax.scan over rounds, lax.scan over waves inside.
    def _make_rounds_fused(self, n_epochs: int, n_rounds: int,
                           donate: bool = True):
        cache = getattr(self, "_fused_cache", None)
        if cache is None:
            cache = self._fused_cache = {}
        key = (n_epochs, n_rounds, donate)
        if key in cache:
            return cache[key]
        if self.mesh is not None and not self.is_hybrid:
            kernel = self._make_wave_sums_sharded(n_epochs, raw=True)
        else:
            # single-device AND hybrid mesh: raw vmap math; on the hybrid
            # mesh GSPMD partitions it from the input placements
            kernel = partial(self._wave_sums_raw, n_epochs=n_epochs)
        server_opt = self.server_optimizer

        def run(params, frozen, data_w, n_w, rng, server_opt_state):
            # data_w leaves [n_waves, wave, cap, ...]; n_w [n_waves, wave]
            n_waves, wave = n_w.shape
            zeros = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, jnp.float32), params
            )

            def one_round(carry, r):
                p, sos = carry
                rkeys = jax.random.split(
                    jax.random.fold_in(rng, r), n_waves * wave
                ).reshape(n_waves, wave)

                def wave_body(acc, xs):
                    d, n, rr = xs
                    psum, lsum, wtot, _ = kernel(p, frozen, d, n, rr)
                    return (
                        agg.tree_add(acc[0], psum),
                        acc[1] + lsum,
                        acc[2] + wtot,
                    ), None

                init = (zeros, jnp.zeros((n_epochs,), jnp.float32),
                        jnp.float32(0.0))
                (psum, lsum, wtot), _ = jax.lax.scan(
                    wave_body, init, (data_w, n_w, rkeys)
                )
                denom = jnp.maximum(wtot, 1e-9)
                aggregate = jax.tree_util.tree_map(
                    lambda s, ref: (s / denom).astype(ref.dtype), psum, p
                )
                if server_opt is not None:
                    p2, sos = _server_update(server_opt, p, aggregate, sos)
                else:
                    p2 = aggregate
                return (p2, sos), lsum / denom

            (p, sos), losses = jax.lax.scan(
                one_round, (params, server_opt_state), jnp.arange(n_rounds)
            )
            return p, sos, losses  # losses [n_rounds, n_epochs]

        # donate=True (the default) aliases the incoming params/server-opt
        # buffers into the outputs — HBM hygiene: no double-buffered
        # global state across the dispatch. frozen (argnum 1) is NOT
        # donated: partition.merge reads it after the call. Callers that
        # must keep the old globals pass donate_buffers=False.
        fn = jax.jit(run, donate_argnums=(0, 5) if donate else ())
        cache[key] = fn
        return fn

    def run_rounds_fused(
        self,
        params: Params,
        data,
        n_samples,
        rng: jax.Array,
        n_rounds: int,
        n_epochs: int = 1,
        wave_size=None,
        server_opt_state=None,
        return_server_opt_state: bool = False,
        donate_buffers: bool = True,
    ):
        """``run_rounds`` as a single XLA dispatch.

        Robust aggregators are not supported here (the fused kernel
        streams weighted sums; order statistics would need every
        client's params live inside the scan) — use :meth:`run_round` /
        :meth:`run_rounds`, which apply them per round.

        ``donate_buffers`` (default True) donates the params/server-opt
        input buffers to XLA — the returned arrays alias them, so the
        old globals are never double-buffered across the dispatch. On
        accelerator backends the caller's ``params`` (and any
        ``server_opt_state`` passed in) are INVALID after this returns;
        pass ``donate_buffers=False`` to keep them (e.g. to re-run from
        the same initial params). CPU ignores donation, so CPU tests are
        unaffected either way.

        Donation-safety audit (aliased buffers never read after the
        fused call): argnum 0 is the post-``_split`` trainable tree and
        argnum 5 the server opt state — neither local is read below the
        ``fn(...)`` call; ``frozen`` IS read by ``partition.merge`` and
        is deliberately not donated.

        The per-round Python of :meth:`run_round` (slicing, accumulation,
        the aggregate divide, the server update) all becomes traced code
        inside one jit: ``lax.scan`` over rounds, ``lax.scan`` over HBM
        waves within a round. One host→device dispatch and one fetch for
        the whole training run — on a remote/tunneled TPU this removes
        every per-round round-trip; on any TPU it lets XLA overlap the
        round boundary with compute. Identical math to ``run_rounds``
        (same fold_in round rngs; bitwise-equal when the cohort needs no
        phantom padding).
        """
        if self.aggregator[0] != "mean":
            raise NotImplementedError(
                "run_rounds_fused streams weighted sums and cannot apply "
                f"the {self.aggregator[0]!r} aggregator; use run_round/"
                "run_rounds for robust aggregation"
            )
        if wave_size == "auto":
            # the fused scan adds only params/opt/accumulator carries on
            # top of the wave kernel auto probes — small next to the
            # conservative plan budget
            wave_size = self.auto_wave_size(params, data, n_samples,
                                            n_epochs=n_epochs)
        params, frozen = self._split(params)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        n_dev = self._clients_per_wave_unit()
        wave = round_up(wave_size if wave_size is not None else c, n_dev)
        n_waves = -(-c // wave)
        c_pad = n_waves * wave

        rngs = jax.random.split(rng, c)  # only shape matters for padding
        data, n_samples, _ = self._pad_wave(data, n_samples, rngs, c_pad)
        data_w = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).reshape((n_waves, wave) + a.shape[1:]),
            data,
        )
        n_w = n_samples.reshape(n_waves, wave)
        if self.mesh is not None:
            shard = NamedSharding(self.mesh, waved_client_spec())
            data_w = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, shard), data_w
            )
            n_w = jax.device_put(n_w, shard)
        if self.is_hybrid:
            params, frozen = self._place_hybrid(params, frozen)

        if self.server_optimizer is not None and server_opt_state is None:
            server_opt_state = self.server_optimizer.init(params)

        fn = self._make_rounds_fused(n_epochs, n_rounds, donate=donate_buffers)
        new_params, server_opt_state, losses = fn(
            params, frozen, data_w, n_w, rng, server_opt_state
        )
        if self.partition is not None:
            new_params = self.partition.merge(new_params, frozen)
        history = np.asarray(losses).reshape(-1).tolist()
        if return_server_opt_state:
            return new_params, history, server_opt_state
        return new_params, history


# The model-sized accumulator of the non-fused wave loop: the previous
# partial sum is donated into the add, so the loop holds ONE psum buffer
# instead of two (old + new) at the accumulation point. Safe by
# construction — the donated array is the previous wave's kernel output,
# owned solely by the loop and rebound immediately.
@partial(jax.jit, donate_argnums=(0,))
def _acc_tree_add(acc, delta):
    return agg.tree_add(acc, delta)


def _server_update(server_optimizer, params, aggregate, opt_state):
    """FedOpt: pseudo-gradient = global − aggregate, fed to optax.
    With optax.sgd(1.0) this reduces exactly to FedAvg assignment."""
    pseudo_grad = jax.tree_util.tree_map(
        lambda g, a: (g.astype(jnp.float32) - a.astype(jnp.float32)).astype(g.dtype),
        params,
        aggregate,
    )
    updates, opt_state = server_optimizer.update(pseudo_grad, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    return new_params, opt_state
