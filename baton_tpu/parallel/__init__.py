from baton_tpu.parallel.mesh import make_mesh, client_sharding, replicated_sharding
from baton_tpu.parallel.engine import FedSim, RoundResult

__all__ = [
    "make_mesh",
    "client_sharding",
    "replicated_sharding",
    "FedSim",
    "RoundResult",
]
