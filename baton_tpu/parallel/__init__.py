from baton_tpu.parallel.mesh import make_mesh, client_sharding, replicated_sharding
from baton_tpu.parallel.engine import FedSim, RoundResult
from baton_tpu.parallel.fedbuff import AsyncResult, FedBuff
from baton_tpu.parallel.personalization import FedPer, PersonalizedRoundResult
from baton_tpu.parallel.clustered import ClusteredFedSim, ClusteredRoundResult
from baton_tpu.parallel.stateful import StatefulClients, StatefulRoundResult
from baton_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
    make_ring_attention_fn,
    make_striped_attention_fn,
    make_ulysses_attention_fn,
)
from baton_tpu.parallel.multihost import initialize_multihost, make_hybrid_mesh
from baton_tpu.parallel.tensor_parallel import (
    shard_params_tp,
    tp_sharding_tree,
    transformer_tp_spec,
)

__all__ = [
    "make_mesh",
    "client_sharding",
    "replicated_sharding",
    "FedSim",
    "RoundResult",
    "FedBuff",
    "AsyncResult",
    "FedPer",
    "PersonalizedRoundResult",
    "StatefulClients",
    "StatefulRoundResult",
    "ClusteredFedSim",
    "ClusteredRoundResult",
    "ring_attention",
    "ulysses_attention",
    "make_ring_attention_fn",
    "make_striped_attention_fn",
    "make_ulysses_attention_fn",
    "initialize_multihost",
    "make_hybrid_mesh",
    "shard_params_tp",
    "tp_sharding_tree",
    "transformer_tp_spec",
]
