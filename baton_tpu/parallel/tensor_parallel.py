"""Tensor parallelism for the transformer zoo — GSPMD sharding rules.

The reference has no model parallelism of any kind (SURVEY §2: its only
"parallelism" is data-parallel federated averaging over HTTP); this
module exists for the BASELINE configs whose models don't fit one chip
(config 4: Llama-8B-class LoRA federated tuning).

The TPU-idiomatic mechanism is **sharding annotation, not manual
collectives**: weights get Megatron-style ``PartitionSpec``s over a
``model`` mesh axis and XLA's GSPMD partitioner inserts the
all-reduce/all-gather collectives. The per-leaf heuristics that used to
live here are now the ``transformer-tp`` rule table in
:mod:`baton_tpu.parallel.partition` — this module is the thin
transformer-flavoured facade over it, kept for its established API
(``shard_params_tp`` / ``tp_sharding_tree`` / ``leaf_tp_sharding``).

This composes with the federated axes by name: a
``Mesh(('clients', 'model'))`` runs vmapped per-client LoRA states on
the ``clients`` axis while the frozen base rides the ``model`` axis —
the rules never mention ``clients``, so GSPMD is free to partition the
client-batched activations over it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from baton_tpu.core.partition import path_str
from baton_tpu.parallel.partition import (  # noqa: F401  (MODEL_AXIS re-exported)
    MODEL_AXIS,
    RuleSet,
    replicated_spec,
    transformer_rules,
)

Params = Any


def transformer_tp_spec(path: str, leaf, axis: str = MODEL_AXIS) -> P:
    """Megatron-style PartitionSpec for one transformer param leaf.

    ``path`` is the slash-joined tree path (core/partition.py:path_str).
    Delegates to the ``transformer-tp`` rule table — see
    :func:`baton_tpu.parallel.partition.transformer_rules` for the
    column/row/vocab/MoE layout rationale.
    """
    return transformer_rules(axis).spec_for(path, leaf)


def _rules_for(spec_fn, axis: str = MODEL_AXIS) -> Optional[RuleSet]:
    """The RuleSet behind ``spec_fn`` when it IS the default table;
    None for a custom callable (legacy extension point)."""
    if spec_fn is transformer_tp_spec:
        return transformer_rules(axis)
    return None


def _custom_leaf_sharding(path, leaf, mesh, spec_fn) -> NamedSharding:
    from baton_tpu.parallel.partition import _divisible

    spec = spec_fn(path, leaf)
    if spec != replicated_spec() and not _divisible(leaf, spec, mesh):
        spec = replicated_spec()
    return NamedSharding(mesh, spec)


def leaf_tp_sharding(
    path: str,
    leaf,
    mesh: Mesh,
    spec_fn: Callable[[str, Any], P] = transformer_tp_spec,
) -> NamedSharding:
    """The TP NamedSharding for a single leaf identified by its tree
    path (with the replicated fallback for non-divisible dims)."""
    rules = _rules_for(spec_fn)
    if rules is not None:
        return rules.leaf_sharding(path, leaf, mesh)
    return _custom_leaf_sharding(path, leaf, mesh, spec_fn)


def shard_params_tp(
    params: Params,
    mesh: Mesh,
    spec_fn: Callable[[str, Any, str], P] = transformer_tp_spec,
    axis: str = MODEL_AXIS,
) -> Params:
    """Place a param tree on ``mesh`` with tensor-parallel shardings.

    Any jitted function consuming the result inherits the layout —
    GSPMD propagates the shardings through the computation and inserts
    the TP collectives. Leaves whose dims don't divide the axis size
    fall back to replicated (correct, just not sharded).
    """
    rules = _rules_for(spec_fn, axis)
    if rules is not None:
        return rules.place(params, mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = [
        jax.device_put(
            leaf,
            _custom_leaf_sharding(
                path_str(p), leaf, mesh, lambda pp, ll: spec_fn(pp, ll, axis)
            ),
        )
        for p, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def tp_sharding_tree(
    params: Params,
    mesh: Mesh,
    spec_fn: Callable[[str, Any], P] = transformer_tp_spec,
) -> Params:
    """The NamedSharding pytree for ``params`` — usable as jit's
    ``in_shardings``/``out_shardings`` so updated params KEEP the TP
    layout across training steps instead of decaying to replicated."""
    rules = _rules_for(spec_fn)
    if rules is not None:
        return rules.shardings(params, mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = [
        _custom_leaf_sharding(path_str(p), leaf, mesh, spec_fn)
        for p, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def describe_tp_sharding(params: Params, mesh: Mesh) -> Dict[str, str]:
    """{path: spec-string} — introspection/debugging helper."""
    return transformer_rules().describe(params, mesh)
