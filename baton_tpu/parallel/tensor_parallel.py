"""Tensor parallelism for the transformer zoo — GSPMD sharding rules.

The reference has no model parallelism of any kind (SURVEY §2: its only
"parallelism" is data-parallel federated averaging over HTTP); this
module exists for the BASELINE configs whose models don't fit one chip
(config 4: Llama-8B-class LoRA federated tuning).

The TPU-idiomatic mechanism is **sharding annotation, not manual
collectives**: weights get Megatron-style ``PartitionSpec``s over a
``model`` mesh axis and XLA's GSPMD partitioner inserts the
all-reduce/all-gather collectives —

* column-parallel (shard the output feature dim): ``wq/wk/wv``,
  ``w_gate/w_up``, ``w1`` (+ its bias ``b1``), ``lm_head``;
* row-parallel (shard the input feature dim): ``wo``, ``w_down``,
  ``w2`` — the matmul's contraction dim, whose partial sums GSPMD
  reduces exactly where Megatron would place its all-reduce;
* vocab-sharded embedding table ``tok_emb``; everything else (norms,
  biases on the model dim, small heads) replicated.

This composes with the federated axes by name: a
``Mesh(('clients', 'model'))`` runs vmapped per-client LoRA states on
the ``clients`` axis while the frozen base rides the ``model`` axis —
the specs below never mention ``clients``, so GSPMD is free to
partition the client-batched activations over it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from baton_tpu.core.partition import path_str

Params = Any

MODEL_AXIS = "model"

# leaf name -> (sharded_dim_kind); see module docstring for the rationale
_COLUMN = ("wq", "wk", "wv", "w_gate", "w_up", "w1", "lm_head")
_ROW = ("wo", "w_down", "w2")
_COLUMN_BIAS = ("b1",)
_VOCAB_ROWS = ("tok_emb",)


def transformer_tp_spec(path: str, leaf, axis: str = MODEL_AXIS) -> P:
    """Megatron-style PartitionSpec for one transformer param leaf.

    ``path`` is the slash-joined tree path (core/partition.py:path_str);
    matching is on the final component, so the rules apply uniformly to
    Llama (swiglu), BERT/ViT (gelu MLP), and LoRA-wrapped variants
    (whose adapter leaves end in the same names under ``lora/``).
    """
    name = path.rsplit("/", 1)[-1]
    if leaf.ndim == 3 and name in ("w_gate", "w_up", "w_down"):
        # stacked MoE expert weights [E, D, F]: expert parallelism
        # shards the expert dim; GSPMD partitions the routed einsums
        # (models/moe.py) and inserts the dispatch collectives
        return P(axis, None, None)
    if leaf.ndim == 2:
        if name in _COLUMN:
            return P(None, axis)
        if name in _ROW:
            return P(axis, None)
        if name in _VOCAB_ROWS:
            return P(axis, None)
    if leaf.ndim == 1 and name in _COLUMN_BIAS:
        return P(axis)
    return P()


def _divisible(leaf, spec: P, mesh: Mesh) -> bool:
    for dim, names in zip(leaf.shape, spec):
        if names is None:
            continue
        if dim % mesh.shape[names]:
            return False
    return True


def leaf_tp_sharding(
    path: str,
    leaf,
    mesh: Mesh,
    spec_fn: Callable[[str, Any], P] = transformer_tp_spec,
) -> NamedSharding:
    """The TP NamedSharding for a single leaf identified by its tree
    path (with the replicated fallback for non-divisible dims)."""
    spec = spec_fn(path, leaf)
    if spec != P() and not _divisible(leaf, spec, mesh):
        spec = P()
    return NamedSharding(mesh, spec)


def shard_params_tp(
    params: Params,
    mesh: Mesh,
    spec_fn: Callable[[str, Any, str], P] = transformer_tp_spec,
    axis: str = MODEL_AXIS,
) -> Params:
    """Place a param tree on ``mesh`` with tensor-parallel shardings.

    Any jitted function consuming the result inherits the layout —
    GSPMD propagates the shardings through the computation and inserts
    the TP collectives. Leaves whose dims don't divide the axis size
    fall back to replicated (correct, just not sharded).
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        spec = spec_fn(path_str(path), leaf, axis)
        if spec != P() and not _divisible(leaf, spec, mesh):
            spec = P()
        out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, out)


def tp_sharding_tree(
    params: Params,
    mesh: Mesh,
    spec_fn: Callable[[str, Any], P] = transformer_tp_spec,
) -> Params:
    """The NamedSharding pytree for ``params`` — usable as jit's
    ``in_shardings``/``out_shardings`` so updated params KEEP the TP
    layout across training steps instead of decaying to replicated."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        spec = spec_fn(path_str(path), leaf)
        if spec != P() and not _divisible(leaf, spec, mesh):
            spec = P()
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def describe_tp_sharding(params: Params, mesh: Mesh) -> dict:
    """{path: spec-string} — introspection/debugging helper."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for path, leaf in flat:
        p = path_str(path)
        spec = transformer_tp_spec(p, leaf)
        if spec != P() and not _divisible(leaf, spec, mesh):
            spec = P()
        out[p] = str(spec)
    return out
