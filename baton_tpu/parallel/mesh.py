"""Device-mesh helpers.

The reference's "cluster" is a dict of HTTP clients
(client_manager.py:100-109). Here the cluster of *simulated* clients is a
``jax.sharding.Mesh`` with a ``clients`` axis: per-client params, opt
state, and data shards live distributed along it, the round broadcast is
replication across it, and FedAvg is a psum over it (ICI within a host,
DCN across hosts — XLA routes the collective).

All PartitionSpecs come from :mod:`baton_tpu.parallel.partition` — this
module only builds meshes and places arrays.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from baton_tpu.parallel.partition import (  # noqa: F401  (re-exported)
    CLIENT_AXIS,
    client_spec,
    replicated_spec,
)


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = (CLIENT_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D (or reshaped n-D) mesh over the available devices.

    For multi-host pods, ``jax.devices()`` already spans hosts; the
    clients axis then runs over ICI+DCN and the psum in
    :func:`baton_tpu.ops.aggregation.psum_weighted_mean` becomes a
    cross-host collective — the TPU-native analogue of the reference's
    HTTP weight gather.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    # All devices go on the first axis; callers wanting a factored
    # multi-axis layout (e.g. clients×model) should construct Mesh
    # directly with their shape.
    shape = (n,) + (1,) * (len(axis_names) - 1)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)


def client_sharding(mesh: Mesh, axis: str = CLIENT_AXIS) -> NamedSharding:
    """Sharding for ``[C, ...]`` stacked client arrays: dim 0 over the
    client mesh axis, everything else replicated."""
    return NamedSharding(mesh, client_spec(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (the global model each round —
    the TPU analogue of the reference's full-state broadcast,
    manager.py:77-86)."""
    return NamedSharding(mesh, replicated_spec())


def shard_client_arrays(tree, mesh: Mesh, axis: str = CLIENT_AXIS):
    """Device-put a ``[C, ...]`` pytree sharded along the client axis."""
    sharding = client_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)


def require_clients_mesh(mesh: Mesh, aggregator_spec, who: str) -> None:
    """Shared construction-time contract for the client-axis wrappers
    (FedPer / StatefulClients / ClusteredFedSim): a clients-only mesh,
    no hybrid model axis, and the mean combine rule (the sharded kernels
    aggregate with psum means; robust order statistics need the full
    stack on one device)."""
    from baton_tpu.parallel.partition import MODEL_AXIS

    if MODEL_AXIS in mesh.axis_names:
        raise ValueError(
            f"{who} shards client state over the {CLIENT_AXIS!r} axis; "
            "the hybrid clients x model mesh is not supported here"
        )
    if CLIENT_AXIS not in mesh.axis_names:
        raise ValueError(
            f"mesh has axes {mesh.axis_names} but {who} needs a "
            f"{CLIENT_AXIS!r} axis"
        )
    if aggregator_spec[0] != "mean":
        raise ValueError(
            f"sharded {who} aggregates with a psum mean; robust rules "
            "need the full stack on one device — use a meshless FedSim"
        )
