"""Sequence parallelism: ring attention and Ulysses (all-to-all) attention.

The reference has no long-context machinery of any kind (SURVEY §5 —
its demo model is a 10->1 linear layer, reference demo.py:15-49); these
kernels exist so the transformer zoo scales past one chip's HBM on
sequence length, the TPU way:

* **Ring attention** (:func:`ring_attention`): K/V blocks rotate around
  the mesh axis via ``lax.ppermute`` (ICI neighbor exchange — the
  topology ring attention was designed for) while each device's Q stays
  put, accumulating exact softmax attention with the online
  (max/sum-rescaling) recurrence. N steps, each overlapping a block
  matmul with a neighbor push; memory per device is O(L/N · L/N)
  scores, never the full L×L.
* **Ring × flash** (:func:`flash_ring_attention`): the same ring, but
  each shard's block math runs the Pallas flash kernel
  (ops/flash_attention.py) — per-shard memory falls from the dense
  [L/N × L/N] fp32 score block to the kernel's O(block), and the block
  matmuls inherit its measured MXU speed. Differentiable via a
  ring-level custom VJP that re-rotates K/V in the backward and runs
  each block's flash backward against the global softmax statistics.
* **Ulysses attention** (:func:`ulysses_attention`): two
  ``lax.all_to_all``s swap sequence-sharding for head-sharding, run
  dense local attention over the full sequence for H/N heads, and swap
  back. Cheaper collectives for moderate L; requires heads % devices
  == 0 (ring has no such constraint).

Both are exact (not approximations) and drop into any model in the zoo
through the ``attention_fn`` seam (:mod:`baton_tpu.models.transformer`)
via :func:`make_ring_attention_fn` / :func:`make_ulysses_attention_fn`,
which shard_map the [B, H, L, Dh] tensors over a sequence mesh axis at
the attention boundary. Additive per-key padding biases ([B, 1, 1, L],
the transformer seam's masking convention) ARE supported: under ring
the bias is sharded with K/V and rotates around the ring with them;
under Ulysses it is all-gathered to full length alongside the
head-resharded K/V. Causal masking is computed from global positions
and is exact; fully-masked future blocks skip their matmuls entirely.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from baton_tpu.parallel.partition import dim_spec

from baton_tpu.parallel.compat import pcast_varying, shard_map

SEQ_AXIS = "seq"

_NEG = -1e30


def _block_scores(q, k, scale):
    """[B,Hq,Lq,Dh] x [B,Hkv,Lk,Dh] -> fp32 [B,Hq,Lq,Lk] with GQA
    head-grouping (query head h reads kv head h // (Hq//Hkv))."""
    b, hq, lq, dh = q.shape
    hkv, lk = k.shape[1], k.shape[2]
    if hq != hkv:
        qg = q.reshape(b, hkv, hq // hkv, lq, dh)
        s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k).reshape(b, hq, lq, lk)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    return s.astype(jnp.float32) * scale


def _block_pv(p, v, hq):
    """[B,Hq,Lq,Lk] probs x [B,Hkv,Lk,Dh] -> [B,Hq,Lq,Dh], GQA-grouped."""
    b, _, lq, lk = p.shape
    hkv = v.shape[1]
    if hq != hkv:
        pg = p.reshape(b, hkv, hq // hkv, lq, lk)
        return jnp.einsum("bhgqk,bhkd->bhgqd", pg, v).reshape(
            b, hq, lq, v.shape[3]
        )
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, causal: bool = False,
                   bias=None, striped: bool = False):
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Call inside ``shard_map`` with q, k, v sharded on the length axis
    ([B, H, L/N, Dh] per device). The online-softmax carry (running max
    ``m``, normalizer ``l``, accumulator ``o``) is rescaled as each new
    K/V block arrives, so the result is bit-for-bit a softmax over the
    full sequence, never materializing L×L scores.

    ``bias`` is the per-shard additive key bias [B, Lk/N] (fp32; -inf to
    mask padding keys) — it is sharded exactly like K/V and rides the
    same ring rotations, so global key positions keep their bias no
    matter which device currently holds the block.

    ``striped=True`` switches the position mapping to the striped
    (round-robin) layout: device ``d``'s local index ``j`` is global
    token ``j*N + d``. Contiguous causal sharding is load-IMBALANCED —
    device 0's queries see one block, device N-1's see all N, so
    wall-clock is the worst device and the causal skip saves energy but
    not time. Striping gives every (query-shard, key-block) pair ~half
    a block of unmasked work, so all devices finish together (the
    "striped attention" layout). Use
    :func:`make_striped_attention_fn`, which handles the token
    permutation at the seam.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, hq, lc, dh = q.shape
    lk = k.shape[2]
    scale = dh ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    # carries start device-invariant but become device-varying inside the
    # loop (ppermute outputs are varying); mark them varying up front so
    # the fori_loop carry types are stable
    def varying(x):
        return pcast_varying(x, axis_name)

    if bias is None:
        # locally-created zeros are invariant; the real bias arrives as a
        # shard_map input (already varying) — both must match the
        # ppermuted b_cur in the loop carry
        bias = varying(jnp.zeros((b, lk), jnp.float32))
    bias = bias.astype(jnp.float32)

    qf = q.astype(jnp.float32)
    o = varying(jnp.zeros((b, hq, lc, dh), jnp.float32))
    m = varying(jnp.full((b, hq, lc), _NEG, jnp.float32))
    l = varying(jnp.zeros((b, hq, lc), jnp.float32))

    def accum(s, o, m, l, k_cur, v_cur, b_cur):
        # after s forward rotations, this device holds the block that
        # originated on device (my - s) mod n
        src = (my - s) % n

        def attend(carry):
            o, m, l = carry
            scores = _block_scores(qf, k_cur.astype(jnp.float32), scale)
            scores = scores + b_cur[:, None, None, :]
            if causal:
                if striped:
                    # striped layout: local j on shard d = token j*n + d
                    q_pos = my + n * jnp.arange(lc)
                    k_pos = src + n * jnp.arange(lk)
                else:
                    q_pos = my * lc + jnp.arange(lc)
                    k_pos = src * lc + jnp.arange(lk)
                scores = jnp.where(
                    q_pos[:, None] >= k_pos[None, :], scores, _NEG
                )
            m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
            p = jnp.exp(scores - m_new[..., None])
            # fully-masked entries: exp(NEG - NEG) == 1 must be zeroed
            p = jnp.where(scores > _NEG / 2, p, 0.0)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1)
            o_new = o * corr[..., None] + _block_pv(
                p, v_cur.astype(jnp.float32), hq
            )
            return o_new, m_new, l_new

        if causal and not striped:
            # contiguous layout: a block strictly in this shard's future
            # is fully masked — skip its two matmuls (≈halves causal ring
            # FLOPs on average, but the savings land unevenly: device 0
            # skips almost everything, device n-1 nothing). The striped
            # layout has no fully-masked pairs to skip; its win is that
            # every pair carries the SAME ~half-block of work.
            return lax.cond(src <= my, attend, lambda c: c, (o, m, l))
        return attend((o, m, l))

    def step(s, carry):
        o, m, l, k_cur, v_cur, b_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        b_cur = lax.ppermute(b_cur, axis_name, perm)
        o, m, l = accum(s, o, m, l, k_cur, v_cur, b_cur)
        return o, m, l, k_cur, v_cur, b_cur

    # step 0 is peeled (local block needs no rotation) and the rotation
    # happens at the top of each remaining step, so exactly n-1 ppermute
    # pairs are issued — a tail rotation whose result is discarded would
    # otherwise waste one neighbor-exchange of full K/V per layer per step
    o, m, l = accum(0, o, m, l, k, v, bias)
    o, m, l, _, _, _ = lax.fori_loop(1, n, step, (o, m, l, k, v, bias))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ======================================================================
# ring × flash: the per-shard block math runs the Pallas flash kernel
# (ops/flash_attention.py) instead of materializing the dense
# [Lq/N, Lk/N] fp32 score block — per-shard memory drops to the flash
# kernel's O(block) and the MXU block math inherits its measured speed.
# Differentiation is a ring-level custom VJP: the forward saves only
# (out, global lse); the backward re-rotates K/V and runs each block's
# flash backward against the GLOBAL statistics — each such call yields
# exactly that block's contribution to the global gradients, with dk/dv/
# dbias accumulators riding the same ring back to their home shard.


def _ring_combine(o, lse, blk_out, blk_lse):
    """Online combination of two normalized partial softmax results over
    disjoint key sets: (o, lse) ⊕ (blk_out, blk_lse)."""
    lse_new = jnp.logaddexp(lse, blk_lse)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_new = jnp.exp(blk_lse - lse_new)[..., None]
    return o * w_old + blk_out.astype(jnp.float32) * w_new, lse_new


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_ring(q, k, v, bias2d, axis_name, causal, block_q, block_k,
                interpret):
    out, _ = _flash_ring_fwd(q, k, v, bias2d, axis_name, causal,
                             block_q, block_k, interpret)
    return out


def _flash_ring_fwd(q, k, v, bias2d, axis_name, causal, block_q, block_k,
                    interpret):
    from baton_tpu.ops.flash_attention import flash_block_fwd

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def varying(x):
        return pcast_varying(x, axis_name)

    if bias2d is None:
        bias2d = varying(jnp.zeros((q.shape[0], k.shape[2]), jnp.float32))

    # peeled diagonal block: the only one needing intra-block causal
    o0, lse0 = flash_block_fwd(q, k, v, bias2d, causal,
                               block_q, block_k, interpret)
    o = o0.astype(jnp.float32)
    lse = lse0

    def step(s, carry):
        o, lse, k_cur, v_cur, b_cur = carry
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        b_cur = lax.ppermute(b_cur, axis_name, perm)
        src = (my - s) % n

        def attend(carry):
            o, lse = carry
            blk_out, blk_lse = flash_block_fwd(
                q, k_cur, v_cur, b_cur, False, block_q, block_k, interpret
            )
            return _ring_combine(o, lse, blk_out, blk_lse)

        if causal:
            # blocks from the future are fully masked: skip them
            o, lse = lax.cond(src < my, attend, lambda c: c, (o, lse))
        else:
            o, lse = attend((o, lse))
        return o, lse, k_cur, v_cur, b_cur

    o, lse, _, _, _ = lax.fori_loop(1, n, step, (o, lse, k, v, bias2d))
    return o.astype(q.dtype), lse


def _flash_ring_save(q, k, v, bias2d, axis_name, causal, block_q, block_k,
                     interpret):
    out, lse = _flash_ring_fwd(q, k, v, bias2d, axis_name, causal,
                               block_q, block_k, interpret)
    return out, (q, k, v, bias2d, out, lse)


def _flash_ring_bwd(axis_name, causal, block_q, block_k, interpret,
                    res, dout):
    from baton_tpu.ops.flash_attention import flash_block_bwd

    q, k, v, bias2d, out, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def varying(x):
        return pcast_varying(x, axis_name)

    had_bias = bias2d is not None
    if bias2d is None:
        bias2d = varying(jnp.zeros((q.shape[0], k.shape[2]), jnp.float32))

    # peeled diagonal block at home
    dq, dk_acc, dv_acc, db_acc = flash_block_bwd(
        q, k, v, bias2d, out, dout, lse, causal,
        block_q, block_k, interpret,
    )
    dq = dq.astype(jnp.float32)
    dk_acc = dk_acc.astype(jnp.float32)
    dv_acc = dv_acc.astype(jnp.float32)

    def step(s, carry):
        dq, dk_acc, dv_acc, db_acc, k_cur, v_cur, b_cur = carry
        # grads ride the ring WITH their K/V block, returning home after
        # the final post-loop rotation
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        b_cur = lax.ppermute(b_cur, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        db_acc = lax.ppermute(db_acc, axis_name, perm)
        src = (my - s) % n

        def attend(carry):
            dq, dk_acc, dv_acc, db_acc = carry
            bdq, bdk, bdv, bdb = flash_block_bwd(
                q, k_cur, v_cur, b_cur, out, dout, lse, False,
                block_q, block_k, interpret,
            )
            return (
                dq + bdq.astype(jnp.float32),
                dk_acc + bdk.astype(jnp.float32),
                dv_acc + bdv.astype(jnp.float32),
                db_acc + bdb,
            )

        if causal:
            dq, dk_acc, dv_acc, db_acc = lax.cond(
                src < my, attend, lambda c: c,
                (dq, dk_acc, dv_acc, db_acc),
            )
        else:
            dq, dk_acc, dv_acc, db_acc = attend(
                (dq, dk_acc, dv_acc, db_acc)
            )
        return dq, dk_acc, dv_acc, db_acc, k_cur, v_cur, b_cur

    dq, dk_acc, dv_acc, db_acc, _, _, _ = lax.fori_loop(
        1, n, step, (dq, dk_acc, dv_acc, db_acc, k, v, bias2d)
    )
    # one final rotation brings each block's accumulated grads home
    dk_acc = lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = lax.ppermute(dv_acc, axis_name, perm)
    db_acc = lax.ppermute(db_acc, axis_name, perm)
    return (
        dq.astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
        db_acc.astype(res[3].dtype) if had_bias else None,
    )


_flash_ring.defvjp(_flash_ring_save, _flash_ring_bwd)


def flash_ring_attention(q, k, v, axis_name: str = SEQ_AXIS,
                         causal: bool = False, bias=None,
                         block_q: int = 512, block_k: int = 1024,
                         interpret=None):
    """Exact ring attention whose per-shard block math is the Pallas
    flash kernel. Call inside ``shard_map`` with q/k/v length-sharded
    ([B, H, L/N, Dh] per device) and ``bias`` the per-shard [B, L/N]
    additive key bias (or None). Differentiable (ring-level custom VJP).
    """
    return _flash_ring(q, k, v, bias, axis_name, causal,
                       block_q, block_k, interpret)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False, bias=None):
    """Exact attention via head<->sequence all-to-all re-sharding.

    Call inside ``shard_map`` with q, k, v sharded on length. Each
    device ends up with the *full* sequence for H/N heads, runs the
    dense kernel, and re-shards back to length. Requires both the query
    and kv head counts to be divisible by the axis size.
    """
    from baton_tpu.models.transformer import dot_product_attention

    n = lax.psum(1, axis_name)

    def to_heads(x):
        # [B, H, L/N, Dh] -> [B, H/N, L, Dh]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    full_bias = None
    if bias is not None:
        # per-shard [B, Lk/N] key bias -> full [B, 1, 1, Lk]: every device
        # attends over the whole sequence after the head re-shard, so it
        # needs every key's bias (cheap — bias is [B, L], not [B, L, Dh])
        full = lax.all_gather(bias.astype(jnp.float32), axis_name,
                              axis=1, tiled=True)
        full_bias = full[:, None, None, :]

    out = dot_product_attention(
        to_heads(q), to_heads(k), to_heads(v), bias=full_bias, causal=causal
    )
    return to_seq(out)


def _seq_sharded_fn(kernel, mesh: Mesh, axis_name: str, with_bias: bool,
                    check_vma: bool = True):
    spec = dim_spec(axis_name, 2, 4)  # [B, H, L, Dh] sharded on L
    bias_spec = dim_spec(axis_name, 1, 2)  # [B, L] key bias, sharded on L

    # check_vma=False only for the flash-ring kernel: its embedded
    # pallas_call out_shape structs carry no varying-manifest
    # annotation; the dense ring/Ulysses kernels keep full VMA checking
    if with_bias:
        @partial(
            shard_map, mesh=mesh,
            in_specs=(spec, spec, spec, bias_spec), out_specs=spec,
            check_vma=check_vma,
        )
        def sharded(q, k, v, bias2d):
            return kernel(q, k, v, bias=bias2d)
    else:
        @partial(
            shard_map, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=check_vma,
        )
        def sharded(q, k, v):
            return kernel(q, k, v)

    return sharded


def _check_seam_bias(bias, b, lk):
    """The transformer seam passes additive key bias as [B, 1, 1, L]
    (transformer.py contract); flatten to the [B, L] the SP kernels
    shard."""
    if bias.shape != (b, 1, 1, lk):
        raise ValueError(
            f"sequence-parallel attention supports per-key bias "
            f"[B, 1, 1, L] only; got {bias.shape}"
        )
    return bias.reshape(b, lk)


def make_ring_attention_fn(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """An ``attention_fn`` for the model zoo: shards [B, H, L, Dh] over
    ``mesh[axis_name]`` on L and runs :func:`ring_attention`. The
    sequence length must be divisible by the axis size. Padded (BERT/
    ViT-style) batches work: the [B, 1, 1, L] key bias is sharded with
    K/V and rotates around the ring."""

    def attention_fn(q, k, v, bias=None, causal=False):
        n = mesh.shape[axis_name]
        if q.shape[2] % n:
            raise ValueError(
                f"ring attention needs sequence length divisible by mesh "
                f"axis {axis_name!r} size {n}; got L={q.shape[2]}"
            )
        kernel = partial(ring_attention, axis_name=axis_name, causal=causal)
        fn = _seq_sharded_fn(kernel, mesh, axis_name,
                             with_bias=bias is not None)
        if bias is None:
            return fn(q, k, v)
        return fn(q, k, v, _check_seam_bias(bias, q.shape[0], k.shape[2]))

    return attention_fn


def make_striped_attention_fn(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """An ``attention_fn`` running CAUSAL ring attention in the striped
    (round-robin) token layout — the load-balanced form of causal
    sequence parallelism.

    Why: under the contiguous layout, causality makes the ring
    imbalanced — the shard holding the sequence tail attends every
    rotated block while the head shard attends one, so the step time is
    the tail shard's and the causal skip saves no wall-clock. Striping
    assigns token ``t`` to device ``t % N``: every (shard, rotated
    block) pair then carries the same ~half block of unmasked work and
    all devices finish each ring step together.

    The permutation in/out of striped order happens here at the seam
    (one gather each way around the attention stack); positions inside
    the kernel are mapped accordingly, so the result equals dense causal
    attention exactly. Non-causal calls fall back to the plain ring
    (striping buys nothing without a triangular mask).
    """

    plain_ring = make_ring_attention_fn(mesh, axis_name)

    def attention_fn(q, k, v, bias=None, causal=False):
        n = mesh.shape[axis_name]
        l = q.shape[2]
        if l % n:
            raise ValueError(
                f"striped attention needs sequence length divisible by "
                f"mesh axis {axis_name!r} size {n}; got L={l}"
            )
        if not causal:
            # striping buys nothing without a triangular mask — delegate
            # to the one ring seam instead of duplicating it
            return plain_ring(q, k, v, bias=bias, causal=False)

        # stripe: token j*n + d -> contiguous slot (d, j), so the
        # contiguous shard_map spec hands device d exactly its stripe
        perm = jnp.arange(l).reshape(l // n, n).T.reshape(l)
        inv = jnp.argsort(perm)
        qs, ks, vs = (x[:, :, perm, :] for x in (q, k, v))
        kernel = partial(ring_attention, axis_name=axis_name, causal=True,
                         striped=True)
        fn = _seq_sharded_fn(kernel, mesh, axis_name,
                             with_bias=bias is not None)
        if bias is None:
            out = fn(qs, ks, vs)
        else:
            b2 = _check_seam_bias(bias, q.shape[0], k.shape[2])
            out = fn(qs, ks, vs, b2[:, perm])
        return out[:, :, inv, :]

    return attention_fn


def make_flash_ring_attention_fn(mesh: Mesh, axis_name: str = SEQ_AXIS,
                                 block_q: int = 512, block_k: int = 1024,
                                 interpret=None):
    """An ``attention_fn`` for the model zoo backed by
    :func:`flash_ring_attention`: sequence parallelism over
    ``mesh[axis_name]`` with the Pallas flash kernel doing each shard's
    block math — the long-context configuration for TPU (ICI ppermute
    between shards, MXU flash blocks within them)."""

    def attention_fn(q, k, v, bias=None, causal=False):
        n = mesh.shape[axis_name]
        if q.shape[2] % n:
            raise ValueError(
                f"ring attention needs sequence length divisible by mesh "
                f"axis {axis_name!r} size {n}; got L={q.shape[2]}"
            )
        kernel = partial(
            flash_ring_attention, axis_name=axis_name, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
        fn = _seq_sharded_fn(kernel, mesh, axis_name,
                             with_bias=bias is not None, check_vma=False)
        if bias is None:
            return fn(q, k, v)
        return fn(q, k, v, _check_seam_bias(bias, q.shape[0], k.shape[2]))

    return attention_fn


def make_ulysses_attention_fn(mesh: Mesh, axis_name: str = SEQ_AXIS):
    """An ``attention_fn`` for the model zoo backed by
    :func:`ulysses_attention`. Head counts must be divisible by the
    axis size. Padded batches work: the per-key bias shard is
    all-gathered next to the head re-shard."""

    def attention_fn(q, k, v, bias=None, causal=False):
        n = mesh.shape[axis_name]
        hq, hkv = q.shape[1], k.shape[1]
        if hq % n or hkv % n:
            raise ValueError(
                f"Ulysses attention needs query AND kv head counts "
                f"divisible by mesh axis {axis_name!r} size {n}; got "
                f"Hq={hq}, Hkv={hkv} (use ring attention for GQA models "
                f"whose kv heads don't divide)"
            )
        if q.shape[2] % n:
            raise ValueError(
                f"Ulysses attention needs sequence length divisible by "
                f"mesh axis {axis_name!r} size {n}; got L={q.shape[2]}"
            )
        kernel = partial(ulysses_attention, axis_name=axis_name,
                         causal=causal)
        fn = _seq_sharded_fn(kernel, mesh, axis_name,
                             with_bias=bias is not None)
        if bias is None:
            return fn(q, k, v)
        return fn(q, k, v, _check_seam_bias(bias, q.shape[0], k.shape[2]))

    return attention_fn
