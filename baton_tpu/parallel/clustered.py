"""Clustered federated learning (IFCA-style): K global models, clients
self-select.

When the cohort is a MIXTURE of populations (different label maps,
different tasks), one global model fits none of them and per-client
personalization (FedPer) can't share strength within a population. The
iterative federated clustering answer (IFCA): keep K global models;
each round every client evaluates all K on its own data, trains the
best-fitting one, and each model aggregates only the clients that chose
it. Assignment and training improve each other until populations
separate.

TPU-first shape: cluster params are ONE stacked pytree ``[K, ...]``;
a round is two vmapped dispatches —

1. assignment: a ``vmap(clients) x vmap(clusters)`` masked-loss grid
   ``[C, K]``, argmin over K;
2. training: every client trains params GATHERED by its assignment
   (vmap over per-client param trees), then per-cluster aggregation is
   one one-hot weighted ``einsum`` — no Python loop over clusters.

Empty clusters keep their previous params (they can win clients later).
The caller threads ``cluster_params`` between rounds like any other
state and owns checkpointing it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from baton_tpu.ops import aggregation as agg
from baton_tpu.parallel.compat import shard_map
from baton_tpu.parallel.engine import FedSim

Params = Any


@dataclasses.dataclass
class ClusteredRoundResult:
    cluster_params: Params      # [K, ...] stacked
    assignments: np.ndarray     # [C] int — chosen cluster per client
    loss_history: jax.Array     # [n_epochs] sample-weighted over clients
    client_losses: jax.Array    # [C, n_epochs]


def _masked_mean_loss(model, p, d, n, r):
    """One client's masked mean loss under ``p`` — the single assignment
    rule used both in rounds and at eval time (they must agree, or a
    client would train one cluster and be scored with another)."""
    losses = model.per_example_loss(p, d, r)
    mask = (jnp.arange(losses.shape[0]) < n).astype(jnp.float32)
    return jnp.sum(losses.astype(jnp.float32) * mask) / jnp.maximum(
        mask.sum(), 1.0
    )


class ClusteredFedSim:
    """IFCA rounds over a :class:`FedSim`'s trainer."""

    def __init__(self, sim: FedSim, n_clusters: int):
        if n_clusters < 2:
            raise ValueError("clustering needs n_clusters >= 2")
        if sim.trainable_predicate is not None:
            raise ValueError(
                "ClusteredFedSim trains full param trees; partitioned "
                "sims are not supported"
            )
        if sim.mesh is not None:
            from baton_tpu.parallel.mesh import require_clients_mesh

            require_clients_mesh(sim.mesh, sim.aggregator, "ClusteredFedSim")
        if sim.aggregator[0] != "mean":
            raise ValueError(
                "per-cluster aggregation is the sample-weighted mean; "
                "robust rules within tiny per-cluster cohorts are "
                "statistically meaningless — filter clients instead"
            )
        if sim.server_optimizer is not None:
            raise ValueError(
                "FedOpt server state per cluster is not threaded here; "
                "configure the FedSim without a server optimizer"
            )
        self.sim = sim
        self.n_clusters = n_clusters
        self._jit_cache: Dict[int, Any] = {}

    def init_clusters(self, rng: jax.Array) -> Params:
        """K independently-initialized models, stacked. Distinct inits
        are what lets assignment break symmetry in round 1."""
        keys = jax.random.split(rng, self.n_clusters)
        trees = [self.sim.model.init(k) for k in keys]
        return agg.tree_stack(trees)

    def _assign_train_combine(self, n_epochs: int, psum_axis=None):
        """The round body; with ``psum_axis`` the per-cluster sums
        reduce across mesh shards (the sharded combine is the same math
        with psums around the one-hot sums)."""
        trainer = self.sim.trainer
        model = self.sim.model
        k_clusters = self.n_clusters
        with_anchor = trainer.regularizer is not None

        def round_fn(cluster_params, data, n_samples, rngs):
            # -- 1. assignment: masked mean loss of every cluster on
            # every client's data ------------------------------------
            def client_losses_vs_clusters(d, n, r):
                return jax.vmap(
                    lambda p: _masked_mean_loss(model, p, d, n, r)
                )(cluster_params)  # [K]

            grid = jax.vmap(client_losses_vs_clusters)(
                data, n_samples, rngs
            )  # [C, K]
            assign = jnp.argmin(grid, axis=1)  # [C]

            # -- 2. train the chosen model per client ---------------
            my_params = jax.tree_util.tree_map(
                lambda a: jnp.take(a, assign, axis=0), cluster_params
            )

            def one(p, d, n, r):
                new_p, _, losses = trainer.train(
                    p, d, n, r, n_epochs, p if with_anchor else None
                )
                return new_p, losses

            trained, closs = jax.vmap(one)(
                my_params, data, n_samples, rngs
            )

            # -- 3. per-cluster sample-weighted mean via one-hot ----
            w = n_samples.astype(jnp.float32)  # [C]
            onehot = jax.nn.one_hot(assign, k_clusters)  # [C, K]
            wk = onehot * w[:, None]  # [C, K]
            denom = jnp.sum(wk, axis=0)  # [K]
            if psum_axis is not None:
                denom = jax.lax.psum(denom, psum_axis)

            def combine(tr, old):
                tr32 = tr.astype(jnp.float32)
                sums = jnp.tensordot(wk, tr32, axes=(0, 0))  # [K, ...]
                if psum_axis is not None:
                    sums = jax.lax.psum(sums, psum_axis)
                mean = sums / jnp.maximum(denom, 1e-9).reshape(
                    (k_clusters,) + (1,) * (tr.ndim - 1)
                )
                keep_old = (denom <= 0).reshape(
                    (k_clusters,) + (1,) * (tr.ndim - 1)
                )
                return jnp.where(
                    keep_old, old.astype(jnp.float32), mean
                ).astype(old.dtype)

            new_clusters = jax.tree_util.tree_map(
                combine, trained, cluster_params
            )
            return new_clusters, assign, closs

        return round_fn

    def _round_fn(self, n_epochs: int):
        if n_epochs not in self._jit_cache:
            self._jit_cache[n_epochs] = jax.jit(
                self._assign_train_combine(n_epochs)
            )
        return self._jit_cache[n_epochs]

    def _round_fn_sharded(self, n_epochs: int):
        key = ("sharded", n_epochs)
        if key not in self._jit_cache:
            from baton_tpu.parallel.mesh import CLIENT_AXIS
            from baton_tpu.parallel.partition import kernel_specs

            in_specs, out_specs = kernel_specs("clustered.round")
            self._jit_cache[key] = jax.jit(shard_map(
                self._assign_train_combine(n_epochs,
                                           psum_axis=CLIENT_AXIS),
                mesh=self.sim.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ))
        return self._jit_cache[key]

    def run_round(
        self,
        cluster_params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: jax.Array,
        n_epochs: int = 1,
    ) -> ClusteredRoundResult:
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        rngs = jax.random.split(rng, c)
        if self.sim.mesh is not None:
            from baton_tpu.parallel.mesh import (
                CLIENT_AXIS,
                shard_client_arrays,
            )

            from baton_tpu.ops.padding import round_up

            n_dev = int(self.sim.mesh.shape[CLIENT_AXIS])
            target = round_up(c, n_dev)
            data_p, n_p, rngs_p = self.sim._pad_wave(
                data, n_samples, rngs, target
            )
            put = lambda t: shard_client_arrays(t, self.sim.mesh)
            new_clusters, assign, closs = self._round_fn_sharded(n_epochs)(
                cluster_params, put(data_p), put(n_p), put(rngs_p)
            )
            assign, closs = assign[:c], closs[:c]
        else:
            new_clusters, assign, closs = self._round_fn(n_epochs)(
                cluster_params, data, n_samples, rngs
            )
        w = n_samples.astype(jnp.float32)
        return ClusteredRoundResult(
            cluster_params=new_clusters,
            assignments=np.asarray(assign),
            loss_history=agg.weighted_scalar_mean(closs, w),
            client_losses=closs,
        )

    def evaluate(
        self,
        cluster_params: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, float]:
        """Each client scored with its best-fitting cluster (fresh
        assignment) — the federation-wide example-weighted aggregate."""
        if rng is None:
            rng = jax.random.key(0)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        rngs = jax.random.split(rng, c)
        totals = self._eval_fn()(cluster_params, data, n_samples, rngs)
        denom = max(float(totals["n"]), 1.0)
        out = {"loss": float(totals["loss_sum"]) / denom, "n": denom}
        if "correct_sum" in totals:
            out["accuracy"] = float(totals["correct_sum"]) / denom
        return out

    def _eval_fn(self):
        # cached like _round_fn (and FedPer._eval_fn): a fresh jit per
        # call would recompile the identical C x K eval program each time
        if "eval" in self._jit_cache:
            return self._jit_cache["eval"]
        from baton_tpu.parallel.engine import client_eval_sums

        model = self.sim.model

        # donation decided no: evaluation never owns its inputs
        @jax.jit  # batonlint: allow[BTL011]
        def eval_all(cluster_params, data, n_samples, rngs):
            def one(d, n, r):
                k = jnp.argmin(jax.vmap(
                    lambda p: _masked_mean_loss(model, p, d, n, r)
                )(cluster_params))
                mine = jax.tree_util.tree_map(
                    lambda a: a[k], cluster_params
                )
                return client_eval_sums(model, mine, d, n, r)

            sums = jax.vmap(one)(data, n_samples, rngs)
            return jax.tree_util.tree_map(jnp.sum, sums)

        self._jit_cache["eval"] = eval_all
        return eval_all
