"""Partial personalization (FedPer-style): per-client personal layers.

Plain FedAvg forces every client onto one global model; under non-IID
shards the canonical fix is to PERSONALIZE part of the network — each
client keeps its own copy of some leaves (classically the head) that
never leaves the device, while the rest ("shared") is trained and
aggregated as usual. The reference has nothing like this (one global
state_dict, manager.py:119-126); it is standard FL-framework surface.

TPU-first shape: personal state is ONE stacked pytree ``[C, ...]`` on
the personal leaves — the same layout as the engine's client data — so a
personalized round is a single vmapped dispatch: vmap merges client c's
personal leaves with the replicated shared leaves, trains the full
model, and splits the result; shared halves aggregate with the sim's
configured rule (mean / trimmed / median via
:func:`baton_tpu.ops.aggregation.apply_aggregator`), personal halves
return as the new stack. On a ``clients`` mesh the same body runs under
``shard_map`` — personal stack and data sharded over chips, shared-leaf
aggregation and the warm-start mean as psum collectives over ICI
(numerically equal to the single-device round, tested).

The returned global params carry the unweighted mean of the personal
leaves purely as a warm start for clients joining later; it is never
trained on directly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from baton_tpu.core.partition import PathPredicate, make_partition
from baton_tpu.ops import aggregation as agg
from baton_tpu.parallel.compat import shard_map
from baton_tpu.parallel.engine import FedSim, client_eval_sums

Params = Any


def _pad_stack(tree: Params, pad: int) -> Params:
    """Pad a ``[C, ...]`` stacked pytree with ``pad`` copies of row 0 —
    phantom rows' values never matter (masked training, weight 0,
    excluded from means) but must be shape/dtype-valid."""
    if pad <= 0:
        return tree
    return jax.tree_util.tree_map(
        lambda a: jnp.concatenate(
            [a, jnp.repeat(a[:1], pad, axis=0)], axis=0
        ),
        tree,
    )


@dataclasses.dataclass
class PersonalizedRoundResult:
    params: Params              # shared aggregated; personal leaves = warm-start mean
    personal_state: Params      # [C, ...] stacked personal leaves
    loss_history: jax.Array     # [n_epochs] sample-weighted
    client_losses: jax.Array    # [C, n_epochs]


class FedPer:
    """Personalized federated training over a :class:`FedSim`'s trainer.

    ``personal(path, leaf) -> bool`` marks the per-client leaves. The
    personal stack threads through rounds exactly like params do — the
    caller owns it (checkpoint it alongside the globals to resume).
    """

    def __init__(self, sim: FedSim, personal: PathPredicate):
        if sim.trainable_predicate is not None:
            raise ValueError(
                "FedPer and a trainable/frozen partition both re-plumb the "
                "param tree; compose by marking frozen leaves neither "
                "personal nor trained instead"
            )
        if sim.server_optimizer is not None:
            raise ValueError(
                "FedPer aggregates shared leaves directly; a FedOpt "
                "server optimizer would be silently ignored — configure "
                "the FedSim without one for personalized rounds"
            )
        if sim.mesh is not None:
            from baton_tpu.parallel.mesh import require_clients_mesh

            require_clients_mesh(sim.mesh, sim.aggregator, "FedPer")
        self.sim = sim
        self.personal_pred = personal
        self.partition = None
        self._jit_cache: Dict[int, Any] = {}

    def _ensure_partition(self, params) -> None:
        if self.partition is None:
            # "trainable" side of the partition = personal leaves
            self.partition = make_partition(params, self.personal_pred)

    def init_personal(self, params: Params, n_clients: int) -> Params:
        """Personal stack initialized by broadcasting the global leaves."""
        self._ensure_partition(params)
        personal, _ = self.partition.split(params)
        return jax.tree_util.tree_map(
            lambda l: jnp.broadcast_to(l, (n_clients,) + l.shape), personal
        )

    def _train_local(self, n_epochs: int):
        """The per-shard body shared by the vmap and shard_map paths."""
        part = self.partition
        trainer = self.sim.trainer
        with_anchor = trainer.regularizer is not None

        def train_local(personal_state, shared, data, n_samples, rngs):
            def one(pers, d, n, r):
                full = part.merge(pers, shared)
                # the client's round-start params are its FedProx
                # anchor (mirrors engine.py's wave kernels)
                new_full, _, losses = trainer.train(
                    full, d, n, r, n_epochs,
                    full if with_anchor else None,
                )
                new_pers, new_shared = part.split(new_full)
                return new_pers, new_shared, losses

            return jax.vmap(one)(personal_state, data, n_samples, rngs)

        return train_local

    def _round_fn(self, n_epochs: int):
        if n_epochs not in self._jit_cache:
            self._jit_cache[n_epochs] = jax.jit(self._train_local(n_epochs))
        return self._jit_cache[n_epochs]

    def _round_fn_sharded(self, n_epochs: int):
        """Mesh path: personal stack / data / rngs sharded over the
        clients axis, shared leaves replicated; shared aggregation and
        the warm-start personal mean are psum collectives over ICI —
        the same layout rule as the engine's sharded wave kernel."""
        key = ("sharded", n_epochs)
        if key not in self._jit_cache:
            from baton_tpu.parallel.mesh import CLIENT_AXIS
            from baton_tpu.parallel.partition import kernel_specs

            train_local = self._train_local(n_epochs)

            def kernel(personal_state, shared, data, n_samples, rngs):
                new_pers, new_shared, closs = train_local(
                    personal_state, shared, data, n_samples, rngs
                )
                w = n_samples.astype(jnp.float32)
                # shared-leaf FedAvg: the one shared psum rule
                shared_agg = agg.tree_cast_like(
                    agg.psum_weighted_mean(new_shared, w, CLIENT_AXIS),
                    shared,
                )
                # warm start: mean over REAL clients only — phantom
                # zero-sample rows carry unchanged round-start leaves
                # and would bias the mean toward no-op
                m = (n_samples > 0).astype(jnp.float32)
                pers_sum = jax.lax.psum(
                    jax.tree_util.tree_map(
                        lambda l: jnp.tensordot(
                            m, l.astype(jnp.float32), axes=(0, 0)
                        ),
                        new_pers,
                    ),
                    CLIENT_AXIS,
                )
                n_real = jnp.maximum(
                    jax.lax.psum(jnp.sum(m), CLIENT_AXIS), 1.0
                )
                pers_mean = jax.tree_util.tree_map(
                    lambda s, ref: (s / n_real).astype(ref.dtype),
                    pers_sum, personal_state,
                )
                loss_hist = agg.psum_weighted_scalar_mean(closs, w,
                                                          CLIENT_AXIS)
                return new_pers, shared_agg, pers_mean, loss_hist, closs

            in_specs, out_specs = kernel_specs("personalization.round")
            # donation decided no: the personal stack is caller
            # state, threaded (and possibly re-read) across rounds
            self._jit_cache[key] = jax.jit(shard_map(  # batonlint: allow[BTL011]
                kernel,
                mesh=self.sim.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            ))
        return self._jit_cache[key]

    def run_round(
        self,
        params: Params,
        personal_state: Optional[Params],
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: jax.Array,
        n_epochs: int = 1,
    ) -> PersonalizedRoundResult:
        self._ensure_partition(params)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        if personal_state is None:
            personal_state = self.init_personal(params, c)
        _, shared = self.partition.split(params)
        rngs = jax.random.split(rng, c)

        if self.sim.mesh is not None:
            from baton_tpu.parallel.mesh import (
                CLIENT_AXIS,
                shard_client_arrays,
            )

            from baton_tpu.ops.padding import round_up

            n_dev = int(self.sim.mesh.shape[CLIENT_AXIS])
            target = round_up(c, n_dev)
            # auto-pad with zero-weight phantoms like the engine's wave
            # path (_pad_wave): phantoms train on all-masked data, carry
            # FedAvg weight 0, and are excluded from the warm-start mean
            data_p, n_p, rngs_p = self.sim._pad_wave(
                data, n_samples, rngs, target
            )
            pers_p = _pad_stack(personal_state, target - c)
            put = lambda t: shard_client_arrays(t, self.sim.mesh)
            new_pers, shared_agg, pers_mean, loss_history, closs = (
                self._round_fn_sharded(n_epochs)(
                    put(pers_p), shared, put(data_p), put(n_p), put(rngs_p)
                )
            )
            unpad = lambda t: jax.tree_util.tree_map(lambda a: a[:c], t)
            return PersonalizedRoundResult(
                params=self.partition.merge(pers_mean, shared_agg),
                personal_state=unpad(new_pers),
                loss_history=loss_history,
                client_losses=closs[:c],
            )

        new_pers, new_shared, closs = self._round_fn(n_epochs)(
            personal_state, shared, data, n_samples, rngs
        )

        w = n_samples.astype(jnp.float32)
        shared_agg = agg.aggregate_stacked(
            self.sim.aggregator, new_shared, n_samples, shared
        )
        # warm start for future clients: mean of REAL clients' personal
        # leaves (zero-sample rows are unchanged broadcasts — excluding
        # them keeps meshless and sharded rounds equal under padding)
        m = (n_samples > 0).astype(jnp.float32)
        n_real = jnp.maximum(jnp.sum(m), 1.0)
        pers_mean = jax.tree_util.tree_map(
            lambda l: (
                jnp.tensordot(m, l.astype(jnp.float32), axes=(0, 0)) / n_real
            ).astype(l.dtype),
            new_pers,
        )
        new_params = self.partition.merge(pers_mean, shared_agg)

        loss_history = agg.weighted_scalar_mean(closs, w)
        return PersonalizedRoundResult(
            params=new_params,
            personal_state=new_pers,
            loss_history=loss_history,
            client_losses=closs,
        )

    def evaluate(
        self,
        params: Params,
        personal_state: Params,
        data: Dict[str, jax.Array],
        n_samples: jax.Array,
        rng: Optional[jax.Array] = None,
    ) -> Dict[str, float]:
        """Personalized evaluation: each client scored on ITS OWN data
        with ITS OWN personal leaves — the metric personalization exists
        for. Returns the example-weighted federation aggregate."""
        self._ensure_partition(params)
        if rng is None:
            rng = jax.random.key(0)
        n_samples = jnp.asarray(n_samples)
        c = int(n_samples.shape[0])
        _, shared = self.partition.split(params)
        rngs = jax.random.split(rng, c)
        eval_all = self._eval_fn()
        totals = eval_all(personal_state, shared, data, n_samples, rngs)
        denom = max(float(totals["n"]), 1.0)
        out = {"loss": float(totals["loss_sum"]) / denom, "n": denom}
        if "correct_sum" in totals:
            out["accuracy"] = float(totals["correct_sum"]) / denom
        return out

    def _eval_fn(self):
        # cached like _round_fn: a fresh jit per call would recompile the
        # identical eval program every round
        if "eval" in self._jit_cache:
            return self._jit_cache["eval"]
        model = self.sim.model
        part = self.partition

        # donation decided no: evaluation never owns its inputs
        @jax.jit  # batonlint: allow[BTL011]
        def eval_all(personal_state, shared, data, n_samples, rngs):
            def one(pers, d, n, r):
                # same sums kernel as FedSim's federated eval — one
                # definition of the accuracy-eligibility rule
                return client_eval_sums(model, part.merge(pers, shared),
                                        d, n, r)

            sums = jax.vmap(one)(personal_state, data, n_samples, rngs)
            return jax.tree_util.tree_map(jnp.sum, sums)

        self._jit_cache["eval"] = eval_all
        return eval_all
