"""HTTP manager — reference-protocol control plane over the pure cores.

Exposes exactly the reference endpoint surface (SURVEY §2.8), same routes
and status codes, under ``/{experiment}/``:

  GET  register      JSON {url?, port}        → {client_id, key}
  GET  heartbeat     JSON {client_id, key}    → "OK" | 401
  GET  clients                                → sanitized client list
  GET  start_round   ?n_epoch= (default 32)   → {client_id: ack} | 400 | 423
  GET  end_round                              → round state JSON
  GET  loss_history                           → JSON list
  POST update        ?client_id&key, tensors  → "OK" | 401 | 410 | 413 | 429
  GET  round_blob/{digest}  ?client_id&key    → BTW1 bytes | 401 | 404
                     (v2 pull data plane; supports HTTP Range resume)
  PUT  update_chunk/{update_id}  ?client_id&key&offset&total
                     → {"offset"} per chunk, final chunk acks like POST
                       update | 409 {"offset": committed} | 413 | 429
  GET  update_chunk/{update_id}  ?client_id&key → {"offset", "total"}
                     committed-offset resume probe (HEAD works too)

Uplink ingest (v2): bodies are size-capped at the door
(``max_upload_bytes`` → 413), admitted through a bounded decode queue
(full → 429 + Retry-After), then decoded/validated/folded OFF the event
loop by the ingest pipeline (server/ingest.py) — the loop only does
auth, round checks, and acceptance bookkeeping, so heartbeats and blob
GETs stay responsive while 64 workers upload at once.

Data plane (v2, default): ``start_round`` serializes the round's params
ONCE into an immutable content-addressed blob (server/blobs.py); each
cohort member is notified with a small JSON envelope — round meta, blob
digest, byte size — and pulls the payload from ``round_blob/{digest}``
with Range-resumable GETs. Workers that still hold the previous round's
blob ("anchor") are offered a cached delta blob (``broadcast_delta=``,
computed once per round via ops/compression.py) and reconstruct
``anchor + delta``, verifying by digest with automatic full-blob
fallback. ``allow_pickle=True`` keeps the reference push protocol — a
full pickled body POSTed per client — for stock reference workers.
Uploads fold into a streaming FedAvg accumulator as they arrive
(``O(model)`` manager memory; robust aggregators keep the buffered
path), and every fan-out runs behind a bounded-concurrency gather
(``fanout_concurrency=``) so C=1024 never means 1024 parallel sockets.

Differences from the reference (each a recorded fix, SURVEY §2.9):
* loss_history / end_round handlers work (items 1-2 were AttributeErrors).
* zero-registered-clients start_round aborts cleanly instead of leaking
  the round lock (item 3).
* culled/evicted clients are dropped from the running round, and a
  straggler watchdog force-finishes rounds past ``round_timeout`` with
  partial aggregation (item 4).
* weight upload is BTW1 (no unpickling network bytes) unless
  ``allow_pickle=True`` opts into reference-demo compatibility.

With ``secure_agg=True`` the experiment speaks the Bonawitz
double-masking protocol (server/secure.py): ``start_round`` runs
AdvertiseKeys (``POST /{worker}/secure_keys``) then ShareKeys
(``POST /{worker}/secure_shares``), the broadcast relays each member's
sealed Shamir-share boxes, uploads arrive pairwise+self masked (uint64
ring elements the server cannot read individually), and finalization
reconstructs dropped members' mask keys and reporters' self-mask seeds
from ≥t shares (``POST /{worker}/secure_unmask``) before dequantizing
the sum.

Aggregation defaults to the engine's weighted tree mean — numerically
the reference formula ``Σ(w·θ)/Σw`` (manager.py:119-126) — with
Byzantine-robust alternatives via ``aggregator="trimmed:<r>"|"median"``
(ops/aggregation.py), and an attached
:class:`baton_tpu.parallel.engine.FedSim` can contribute a whole TPU-
simulated cohort to the same round as one weighted participant, so real
edge clients and on-mesh simulated clients compose in one federation.
Workers may upload top-k sparse round deltas (``compress=`` on the
worker; ops/compression.py) — reconstructed here against the round's
broadcast anchor.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import math
import os
import re
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

import aiohttp
from aiohttp import web
import jax
import jax.numpy as jnp
import numpy as np

from baton_tpu.core.model import FedModel
from baton_tpu.obs import alerts as obs_alerts
from baton_tpu.obs import compute as obs_compute
from baton_tpu.obs import forensics as obs_forensics
from baton_tpu.obs import runbooks as obs_runbooks
from baton_tpu.ops import aggregation as agg
from baton_tpu.server import replication, wire
from baton_tpu.server.blobs import BlobStore
from baton_tpu.server.fleet import ClientLedger
from baton_tpu.server.ingest import ChunkSession, IngestPipeline
from baton_tpu.server.registry import AuthError, ClientRegistry, UnknownClient
from baton_tpu.server.rounds import RoundInProgress, RoundManager
from baton_tpu.server.state import params_to_state_dict, state_dict_to_params
from baton_tpu.server.utils import (
    BodyTooLarge,
    PeriodicTask,
    bounded_gather,
    json_clean,
    read_body_capped,
    read_json_capped,
)
from baton_tpu.utils import profiling, tracing
from baton_tpu.utils.metrics import LoopLagProbe, Metrics
from baton_tpu.utils.slog import RoundsLog, maybe_rotate_jsonl
from baton_tpu.utils.tracing import trace_headers

DEFAULT_N_EPOCH = 32  # reference manager.py:52-55

_log = logging.getLogger(__name__)

#: worker self-reported timing fields accepted off the wire (anything
#: else in an update's ``meta["timings"]`` is dropped at the door)
_TIMING_KEYS = ("train_s", "upload_s", "hb_rtt_s")


def _clean_timings(raw: Any) -> Optional[dict]:
    """Sanitize a worker/edge-supplied ``timings`` dict: known keys
    only, finite non-negative floats, or ``None`` when nothing valid
    survives — ledger observations never carry attacker-shaped data."""
    if not isinstance(raw, dict):
        return None
    out = {}
    for key in _TIMING_KEYS:
        val = raw.get(key)
        if (
            isinstance(val, (int, float))
            and not isinstance(val, bool)
            and math.isfinite(val)
            and val >= 0
        ):
            out[key] = float(val)
    return out or None


#: compute-record fields accepted off the wire (obs/compute.py schema).
#: Numeric keys may also legitimately arrive as ``None`` — but only
#: with a non-empty ``<key>_reason``/``<key>_source`` string sibling
#: (the null-with-reason invariant, enforced here at the door).
_COMPUTE_NUM_KEYS = (
    "train_s", "steps", "n_chips", "samples_per_sec",
    "samples_per_sec_per_chip", "mfu", "flops_per_sample",
    "compile_s", "recompiles", "peak_hbm_gb",
)
_COMPUTE_STR_KEYS = (
    "device_kind", "model_family",
)
_COMPUTE_BOOL_KEYS = ("cache_hit", "recompile_storm")
_COMPUTE_MAX_STR = 256


def _clean_compute(raw: Any) -> Optional[dict]:
    """Sanitize a worker/edge-supplied compute record: known keys only,
    finite non-negative numbers, bounded strings, and the
    null-with-reason invariant — a null metric WITHOUT a reason/source
    sibling is dropped (never stored as a bare null), and reason
    strings survive only next to the field they excuse."""
    if not isinstance(raw, dict):
        return None
    out: dict = {}
    for key in _COMPUTE_NUM_KEYS:
        val = raw.get(key)
        if (
            isinstance(val, (int, float))
            and not isinstance(val, bool)
            and math.isfinite(val)
            and val >= 0
        ):
            out[key] = float(val)
        elif val is None and key in raw:
            why = raw.get(f"{key}_reason") or raw.get(f"{key}_source")
            if isinstance(why, str) and why:
                out[key] = None
                out[f"{key}_reason"] = why[:_COMPUTE_MAX_STR]
    for key in _COMPUTE_STR_KEYS:
        val = raw.get(key)
        if isinstance(val, str) and val:
            out[key] = val[:_COMPUTE_MAX_STR]
        elif val is None and key in raw:
            why = raw.get(f"{key}_reason") or raw.get(f"{key}_source")
            if isinstance(why, str) and why:
                out[key] = None
                out[f"{key}_reason"] = why[:_COMPUTE_MAX_STR]
    for key in _COMPUTE_BOOL_KEYS:
        if isinstance(raw.get(key), bool):
            out[key] = raw[key]
    # provenance sources riding next to MEASURED values (e.g.
    # peak_hbm_gb_source = "allocator" | "xla_memory_analysis")
    for key in _COMPUTE_NUM_KEYS:
        src = raw.get(f"{key}_source")
        if out.get(key) is not None and isinstance(src, str) and src:
            out[f"{key}_source"] = src[:_COMPUTE_MAX_STR]
    return out or None


class _BadUpload(ValueError):
    """An upload rejected with a *specific* 400 message (unknown
    compression scheme, compressed-in-secure-round, ...). Raised from
    the off-loop decode stage so the handler can distinguish precise
    rejections from the generic "Bad Payload" catch-all."""

    def __init__(self, msg: str) -> None:
        super().__init__(msg)
        self.msg = msg


class Manager:
    """Top-level container (reference manager.py:10-18): holds the aiohttp
    app and registered experiments."""

    def __init__(self, app: web.Application):
        self.app = app
        self.experiments: list[Experiment] = []

    def register_experiment(
        self,
        model: FedModel,
        params=None,
        name: Optional[str] = None,
        **kwargs: Any,
    ) -> "Experiment":
        name = name or getattr(model, "name", None) or f"exp_{len(self.experiments)}"
        experiment = Experiment(name, self.app, model, params=params, **kwargs)
        self.experiments.append(experiment)
        return experiment


class Experiment:
    """One federated experiment: global params + membership + rounds."""

    def __init__(
        self,
        name: str,
        app: web.Application,
        model: FedModel,
        params=None,
        client_ttl: float = 300.0,
        round_timeout: Optional[float] = None,
        allow_pickle: bool = False,
        rng_seed: int = 0,
        start_background_tasks: bool = True,
        checkpoint_dir: Optional[str] = None,
        checkpoint_keep: int = 3,
        metrics: Optional[Metrics] = None,
        secure_agg: bool = False,
        secure_scale_bits: int = 16,
        secure_phase_timeout: Optional[float] = None,
        aggregator: str = "mean",
        streaming_aggregation: bool = True,
        cohort_fraction: float = 1.0,
        min_cohort: int = 1,
        broadcast_quantize_bits: Optional[int] = None,
        broadcast_delta: Optional[str] = None,
        delta_chain_depth: int = 2,
        fanout_concurrency: int = 64,
        journal_path: Optional[str] = None,
        journal_fsync: Any = "always",
        recovery_policy: str = "resume",
        max_upload_bytes: Optional[int] = 1 << 30,
        ingest_workers: int = 4,
        ingest_queue_depth: int = 64,
        fold_shards: int = 1,
        max_chunk_sessions: int = 64,
        trace_dir: Optional[str] = None,
        rounds_log_path: Optional[str] = None,
        clients_log_path: Optional[str] = None,
        health_window: int = 32,
        metrics_history_interval_s: float = 5.0,
        alert_rules: Optional[list] = None,
        alerts_log_path: Optional[str] = None,
        alerts_interval_s: float = 1.0,
        alerts_rounds_window: int = 8,
        forensics_dir: Optional[str] = None,
        forensics_max_bundles: int = 16,
        runbook_rules: Optional[Any] = None,
        runbooks_log_path: Optional[str] = None,
        retention_interval_s: float = 60.0,
        trace_spool_max_age_s: float = 3600.0,
        trace_spool_max_files: int = 512,
        jsonl_max_bytes: Optional[int] = 64 * 1024 * 1024,
        ha_role: Optional[str] = None,
        ha_replica_id: Optional[str] = None,
        ha_standbys: Optional[list] = None,
        ha_replicas: Optional[dict] = None,
        ha_lease_s: float = 3.0,
        ha_ship_interval_s: float = 0.5,
        ha_promote_grace_s: float = 1.0,
        ha_auto_promote: bool = True,
        ha_token: Optional[str] = None,
        chunk_spill_dir: Optional[str] = None,
        journal_payloads: bool = True,
        journal_payload_max_bytes: Optional[int] = 8 * 1024 * 1024,
    ):
        """``aggregator``: ``"mean"`` (sample-weighted FedAvg, reference
        manager.py:119-126), or Byzantine-robust ``"trimmed:<ratio>"`` /
        ``"median"`` (coordinate-wise order statistics over the round's
        reporters, unweighted — a poisoned client must not buy influence
        via a claimed n_samples; ops/aggregation.py).

        ``cohort_fraction``: the FedAvg paper's C — each round samples
        this fraction of registered clients (at least ``min_cohort``)
        for notification instead of broadcasting to everyone (the
        reference's only mode, manager.py:77-86). Unsampled clients
        simply skip the round; their next heartbeat keeps them
        registered.

        ``broadcast_quantize_bits`` (8 or 16): downlink compression —
        each round's broadcast ships stochastically-quantized weights
        (ops/compression.py::quantize_state_dict), 4x/2x smaller on the
        wire. All cohort members dequantize the SAME tensors, so every
        client still starts from identical params, and sparse uplink
        deltas are reconstructed against the dequantized anchor.

        ``broadcast_delta`` (``"q8"`` | ``"q16"`` | ``"topk:<frac>"`` |
        ``"topk:<frac>:qN"``): downlink delta blobs. Each round the
        manager additionally encodes prev_round → this_round under this
        spec, ONCE, and the round's broadcast becomes the (bit-defined)
        reconstruction ``anchor + delta`` — so a worker holding the
        previous round's blob downloads only the small delta, verifies
        its reconstruction by digest, and falls back to the full blob
        automatically. Mutually exclusive with ``allow_pickle`` (push
        clients never pull) and ``broadcast_quantize_bits`` (the delta
        spec already carries the lossy-encoding budget).

        ``delta_chain_depth``: how many consecutive rounds of delta
        blobs to retain and advertise (``delta_broadcast`` mode). A
        worker whose anchor is ``k < delta_chain_depth`` rounds old
        reconstructs the current round through ``k`` small delta pulls
        (each hop digest-verified) instead of one full-blob pull.
        Depth 1 disables chaining (single-hop deltas only); the default
        2 covers a worker that missed one round. Raising it trades blob
        store bytes (one delta blob per retained hop) for cheaper
        re-sync of longer absences.

        ``streaming_aggregation``: with the ``"mean"`` aggregator, fold
        each accepted upload into a running ``(weighted_sum, weight)``
        accumulator and free its tensors immediately — O(model) manager
        memory regardless of cohort size, bit-identical to the buffered
        fold (tests/test_dataplane.py). ``False`` keeps the buffered
        path (per-client state_dicts retained until ``end_round``) for
        introspection/debugging. Robust aggregators always buffer —
        order statistics need the whole cohort.

        ``fanout_concurrency``: cap on simultaneous outbound requests
        for every manager fan-out (notify broadcast, secure phases) —
        see :func:`baton_tpu.server.utils.bounded_gather`.

        ``journal_path``: enable the control-plane write-ahead journal
        (server/journal.py) at this path. On construction the journal is
        replayed: the client registry (ids, auth keys, callback URLs)
        and round counter come back, and an in-flight round is handled
        per ``recovery_policy`` — ``"resume"`` re-announces the round to
        its surviving participants under its original name so their
        trained updates still land; ``"abort"`` discards it cleanly.
        Secure-aggregation rounds always abort on recovery: the mask/
        share state lived only in the dead process. ``journal_fsync``
        is the :class:`~baton_tpu.server.journal.Journal` policy
        (``"always"`` | ``"never"`` | seconds between fsyncs).

        ``max_upload_bytes``: admission cap on any single uplink body
        (update POST, chunk PUT, or a chunked upload's declared total).
        Oversized requests get ``413`` at the door — Content-Length is
        checked before the body is read, and streamed reads are cut off
        at the cap. ``None`` disables the cap.

        ``ingest_workers`` / ``ingest_queue_depth``: the uplink ingest
        pipeline (server/ingest.py). Body decode, payload validation,
        top-k decompression, and the streaming fold run on a pool of
        ``ingest_workers`` threads so the event loop only does auth/
        round checks and hand-off; at most ``ingest_queue_depth``
        uploads may be in the decode stage at once, beyond which the
        manager answers ``429`` with ``Retry-After`` (the worker
        outbox's backoff honors it). ``ingest_workers=0`` disables the
        pipeline and restores the legacy fully-on-loop path.

        ``fold_shards``: number of parallel fold lanes for the
        streaming accumulator. The default 1 folds in acceptance order
        (bit-deterministic, same as the on-loop fold); ``>1`` opts into
        N partial accumulators merged at ``end_round`` — equal to the
        sequential fold up to fp32 reduction order.

        ``max_chunk_sessions``: cap on concurrently assembling chunked
        uploads (each can hold up to ``max_upload_bytes``); beyond it
        new sessions get ``429``.

        ``trace_dir``: enable the distributed round tracer's crash
        spool (baton_tpu/utils/tracing.py): every finished span is
        appended to ``<trace_dir>/<trace_id>.jsonl`` eagerly, so a
        manager killed mid-round loses its heap but not its spans, and
        the recovered incarnation's ``GET /{name}/rounds/{rid}/trace``
        still covers both incarnations. Tracing itself (in-memory
        spans, traceparent propagation, the trace endpoint) is always
        on; the spool is the only part that needs a path.

        ``rounds_log_path``: append one SLO summary record per
        finished/aborted round (participants, stragglers, per-round
        counter deltas, phase durations) to this JSONL file — the data
        contract the scenario harness consumes
        (baton_tpu/utils/slog.py::RoundsLog).

        ``clients_log_path``: persist the fleet health ledger's
        per-client per-round observations to this JSONL file
        (``clients.jsonl``, same crash-safe append discipline as
        ``rounds.jsonl``). The in-memory ledger + classifications
        (``GET /{name}/fleet/health``) are always on; ``health_window``
        bounds each client's observation ring.

        ``metrics_history_interval_s``: period of the background task
        that snapshots the metrics registry into the bounded history
        ring behind ``GET /{name}/metrics/history`` (0 disables it).

        ``alert_rules``: declarative alert rule pack
        (:mod:`baton_tpu.obs.alerts`) evaluated every
        ``alerts_interval_s`` against this node's metric namespace, the
        metrics-history ring, and the last ``alerts_rounds_window``
        round records. ``None`` means the default pack; ``[]`` disables
        evaluation (the ``GET /{name}/alerts`` endpoint stays up).
        Lifecycle transitions append to ``alerts_log_path``
        (``alerts.jsonl``, same crash-safe discipline as
        ``rounds.jsonl``). Rules marked ``capture: true`` arm a
        forensics bundle for the next finished round, stored
        content-addressed under ``forensics_dir`` (in-memory-only when
        unset) and served at ``GET /{name}/forensics/{digest}``; at
        most ``forensics_max_bundles`` are retained.

        ``runbook_rules``: declarative remediation rules
        (:mod:`baton_tpu.obs.runbooks`) the manager ACTUATES — biased/
        over-provisioned cohort sampling, adaptive round deadlines,
        FedBuff-style early finish, recompile-storm shape pinning —
        evaluated on the alerting tick against the alert view plus the
        fleet ledger's ``fleet.*`` classification metrics. Unlike
        alerts, runbooks are opt-in: ``None`` (default) disables
        actuation entirely (``GET /{name}/runbooks`` stays up);
        ``"default"`` selects
        :data:`~baton_tpu.obs.runbooks.DEFAULT_RUNBOOKS`. Every
        applied actuation is stamped into the round's ``rounds.jsonl``
        record (``actuations``) with its triggering alert/metric, and
        rule enter/exit transitions append to ``runbooks_log_path``
        (``runbooks.jsonl``). Actuation is an advisory plane: any
        runbook failure falls back to the un-actuated behavior.

        Retention: every ``retention_interval_s`` a background task
        GCs the trace spool (age ``trace_spool_max_age_s`` / count
        ``trace_spool_max_files``, exempting traces referenced by
        retained forensics bundles) and rotates ``rounds.jsonl`` /
        ``clients.jsonl`` once they exceed ``jsonl_max_bytes``
        (``None`` disables rotation).

        Replication (server/replication.py): ``ha_role`` opts this
        replica into the control-plane HA protocol — ``"active"`` ships
        its journal to ``ha_standbys`` (base URLs) and renews an
        epoch-numbered lease every ``ha_ship_interval_s``;
        ``"standby"`` applies shipped WAL segments at
        ``POST /{name}/wal_segment``, refuses all serving routes 503,
        and (with ``ha_auto_promote``) promotes itself once the lease
        has been expired for ``ha_promote_grace_s``. Both roles require
        ``journal_path``. ``ha_replicas`` (``{replica_id: base_url}``)
        additionally builds the :class:`ExperimentTopology` hash-ring
        assignment of experiments to replicas; a heartbeat landing on
        the wrong replica gets a 307 redirect carrying the refreshed
        topology map. ``ha_token`` authenticates wal_segment POSTs.
        ``chunk_spill_dir`` spills chunk-upload sessions to disk so a
        restart keeps each committed prefix; ``journal_payloads``
        journals accepted update payloads (bodies up to
        ``journal_payload_max_bytes``) so a resumed round reuses
        already-delivered updates instead of re-training reporters."""
        if secure_agg and allow_pickle:
            raise ValueError(
                "secure_agg is incompatible with allow_pickle: reference-"
                "protocol pickle workers cannot speak the masking protocol"
            )
        if broadcast_quantize_bits not in (None, 8, 16):
            raise ValueError("broadcast_quantize_bits must be None, 8 or 16")
        if broadcast_quantize_bits is not None and allow_pickle:
            raise ValueError(
                "broadcast quantization is incompatible with allow_pickle: "
                "reference-protocol workers cannot dequantize"
            )
        self.broadcast_quantize_bits = broadcast_quantize_bits
        self._delta_spec: Optional[dict] = None
        if broadcast_delta is not None:
            if allow_pickle:
                raise ValueError(
                    "broadcast_delta is incompatible with allow_pickle: "
                    "reference-protocol workers use the push path and "
                    "never pull blobs"
                )
            if broadcast_quantize_bits is not None:
                raise ValueError(
                    "broadcast_delta and broadcast_quantize_bits are "
                    "mutually exclusive: the delta spec already carries "
                    "the lossy-encoding budget"
                )
            from baton_tpu.ops.compression import parse_delta_spec

            self._delta_spec = parse_delta_spec(broadcast_delta)
        if fanout_concurrency < 1:
            raise ValueError(
                f"fanout_concurrency must be >= 1, got {fanout_concurrency}"
            )
        self.fanout_concurrency = int(fanout_concurrency)
        self._broadcast_anchor_sd: Optional[dict] = None
        # v2 pull data plane: content-addressed blobs + delta anchoring
        self._blobs = BlobStore()
        self._prev_blob_sd: Optional[dict] = None
        self._prev_blob_digest: Optional[str] = None
        # consecutive recent delta-hop descriptors {digest, size, from,
        # to}, oldest first, hop[i]["to"] == hop[i+1]["from"] — retained
        # up to ``delta_chain_depth`` rounds so a worker anchored k
        # rounds back (k < depth) chains k small delta pulls instead of
        # paying a full pull
        self._delta_hops: list = []
        if delta_chain_depth < 1:
            raise ValueError(
                f"delta_chain_depth must be >= 1, got {delta_chain_depth}"
            )
        self.delta_chain_depth = int(delta_chain_depth)
        # streaming FedAvg accumulator for the round in flight (None for
        # robust/secure rounds, which need the buffered path)
        self._stream_acc = None
        # owns every _stream_acc mutation: fold-lane threads add() into
        # it while the loop swaps/rebuilds it (and the simulated-cohort
        # path add()s on the loop) — an asyncio.Lock cannot exclude the
        # lanes, so this must be a threading.Lock on BOTH sides
        self._acc_lock = threading.Lock()
        self.streaming_aggregation = bool(streaming_aggregation)
        if max_upload_bytes is not None and max_upload_bytes < 1:
            raise ValueError(
                f"max_upload_bytes must be >= 1 or None, got {max_upload_bytes}"
            )
        if ingest_workers < 0:
            raise ValueError(
                f"ingest_workers must be >= 0, got {ingest_workers}"
            )
        if fold_shards < 1:
            raise ValueError(f"fold_shards must be >= 1, got {fold_shards}")
        if max_chunk_sessions < 1:
            raise ValueError(
                f"max_chunk_sessions must be >= 1, got {max_chunk_sessions}"
            )
        self.max_upload_bytes = (
            None if max_upload_bytes is None else int(max_upload_bytes)
        )
        self.fold_shards = int(fold_shards)
        self.max_chunk_sessions = int(max_chunk_sessions)
        if not (0.0 < cohort_fraction <= 1.0):
            raise ValueError(
                f"cohort_fraction must be in (0, 1], got {cohort_fraction}"
            )
        self.cohort_fraction = cohort_fraction
        self.min_cohort = max(1, int(min_cohort))
        import random as _random

        self._cohort_rng = _random.Random(rng_seed)
        self.aggregator = agg.parse_aggregator(aggregator)
        if secure_agg and self.aggregator[0] != "mean":
            raise ValueError(
                "robust aggregators are incompatible with secure_agg: the "
                "server only ever sees the cohort SUM, never per-client "
                "updates to trim or take medians over"
            )
        if recovery_policy not in ("resume", "abort"):
            raise ValueError(
                f"recovery_policy must be 'resume' or 'abort', "
                f"got {recovery_policy!r}"
            )
        self.recovery_policy = recovery_policy
        if ha_role not in (None, "active", "standby"):
            raise ValueError(
                f"ha_role must be None, 'active' or 'standby', got {ha_role!r}"
            )
        if ha_role is not None and journal_path is None:
            raise ValueError(
                "ha_role requires journal_path: the WAL is the "
                "replication channel"
            )
        self.ha_role = ha_role
        self.ha_replica_id = ha_replica_id or name
        self.ha_lease_s = float(ha_lease_s)
        self.ha_ship_interval_s = float(ha_ship_interval_s)
        self.ha_promote_grace_s = float(ha_promote_grace_s)
        self.ha_auto_promote = bool(ha_auto_promote)
        self.ha_token = ha_token
        self.ha_epoch = 0
        self._ha_standbys = [
            u.rstrip("/") for u in (ha_standbys or [])
        ]
        self._ha_replica_urls = {
            str(rid): str(url).rstrip("/")
            for rid, url in (ha_replicas or {}).items()
        }
        self._wal_shipper = None
        self._wal_receiver = None
        self._ha_lease: Optional[dict] = None
        self._recovered_ha_epoch = 0
        self.journal_payloads = bool(journal_payloads)
        self.journal_payload_max_bytes = (
            None
            if journal_payload_max_bytes is None
            else int(journal_payload_max_bytes)
        )
        self.name = name
        self.app = app
        self.model = model
        self.params = params if params is not None else model.init(jax.random.key(rng_seed))
        self.journal = None
        self._journal_path = journal_path
        self._journal_fsync = journal_fsync
        if journal_path is not None and ha_role != "standby":
            from baton_tpu.server.journal import Journal

            self.journal = Journal(journal_path, fsync=journal_fsync)
        self.registry = ClientRegistry(
            name, client_ttl=client_ttl, journal=self.journal
        )
        self.rounds = RoundManager(
            name, round_timeout=round_timeout, journal=self.journal
        )
        self.metrics = metrics or Metrics()
        # HA wiring (server/replication.py): a standby owns no Journal —
        # its journal FILE is written verbatim by the WalReceiver and
        # only becomes a live Journal at promote()
        if ha_role == "standby":
            self._wal_receiver = replication.WalReceiver(
                journal_path, metrics=self.metrics
            )
        self._ha_topology = (
            replication.ExperimentTopology(sorted(self._ha_replica_urls))
            if self._ha_replica_urls
            else None
        )
        # Distributed round tracing. The service label is
        # per-INCARNATION (random suffix): a chaos test runs a killed
        # manager and its replacement in one OS process, and the trace
        # must attribute each span to the incarnation that emitted it.
        self.tracer = tracing.Tracer(
            service=f"manager#{os.urandom(2).hex()}", spool_dir=trace_dir
        )
        self.rounds_log = (
            RoundsLog(rounds_log_path) if rounds_log_path else None
        )
        # fleet health plane: per-client observation ledger + advisory
        # anomaly classification (server/fleet.py)
        self.fleet = ClientLedger(
            window=health_window,
            log_path=clients_log_path,
            metrics=self.metrics,
            node="manager",
        )
        self.metrics_history_interval_s = float(metrics_history_interval_s)
        # alerting plane (obs/alerts.py): rules evaluated on a periodic
        # tick against the metric view; capture-flagged rules arm a
        # forensics bundle for the next round. Advisory, like the fleet
        # ledger — nothing here may break round completion.
        self.alerts_interval_s = float(alerts_interval_s)
        self.clients_log_path = clients_log_path
        self.retention_interval_s = float(retention_interval_s)
        self.trace_spool_max_age_s = float(trace_spool_max_age_s)
        self.trace_spool_max_files = int(trace_spool_max_files)
        self.jsonl_max_bytes = (
            None if jsonl_max_bytes is None else int(jsonl_max_bytes)
        )
        # mirror of appended rounds.jsonl records: the alert evaluator
        # derives its rounds.* series from this deque so an evaluation
        # tick never does blocking file IO on the loop
        self._recent_rounds: deque = deque(maxlen=64)
        self.forensics = obs_forensics.ForensicsStore(
            forensics_dir, max_bundles=forensics_max_bundles
        )
        # the pending capture armed by a firing capture:true rule —
        # consumed by the next _finish_round_obs
        self._forensics_armed: Optional[dict] = None
        self.alerts = obs_alerts.AlertEngine(
            alert_rules,
            log_path=alerts_log_path,
            metrics=self.metrics,
            node="manager",
            rounds_window=alerts_rounds_window,
            on_capture=self._arm_forensics,
        )
        # runbook plane (obs/runbooks.py): remediations the manager
        # actually applies. Opt-in, unlike alerts — observation is free,
        # actuation changes round behavior, so None means NO rules.
        if runbook_rules == "default":
            runbook_rules = obs_runbooks.DEFAULT_RUNBOOKS
        self.runbooks = obs_runbooks.RunbookEngine(
            runbook_rules or (),
            log_path=runbooks_log_path,
            metrics=self.metrics,
            node="manager",
        )
        # actuations applied to the round in flight, stamped into its
        # rounds.jsonl record by _finish_round_obs (the explainability
        # contract: every actuation names its trigger)
        self._pending_actuations: list = []
        # the notify fan-out of the round in flight (participation
        # denominator for the ledger's missed-round accounting)
        self._round_cohort: list = []
        self._loop_probe = LoopLagProbe(self.metrics)
        # counter snapshot at round start — rounds.jsonl records deltas
        self._slo_base: Optional[dict] = None
        # uplink ingest pipeline (None = legacy fully-on-loop path)
        self._ingest = (
            IngestPipeline(
                workers=ingest_workers,
                queue_depth=ingest_queue_depth,
                fold_shards=fold_shards,
                metrics=self.metrics,
                tracer=self.tracer,
            )
            if ingest_workers > 0
            else None
        )
        # chunked resumable uploads: (client_id, update_id) → ChunkSession
        self.chunk_spill_dir = chunk_spill_dir
        self._chunks: Dict[tuple, ChunkSession] = {}
        if chunk_spill_dir is not None:
            self._chunks = ChunkSession.restore_sessions(chunk_spill_dir)
            if self._chunks:
                self.metrics.inc(
                    "chunk_sessions_restored", float(len(self._chunks))
                )
        # round-robin shard cursor for fold_shards>1 (reset per round)
        self._fold_rr = 0
        # client_ids mid-acceptance across an off-loop decompress await
        # (buffered compressed path) — treated like client_responses for
        # duplicate suppression
        self._accepting: set = set()
        # (edge_client_id, update_id) pairs of edge partials already
        # folded this round: the edge's at-least-once ship retries after
        # a lost 200, and re-folding a cohort partial would double every
        # contributor's weight at once
        self._edge_partial_ids: set = set()
        self.checkpointer = None
        if checkpoint_dir is not None:
            from baton_tpu.utils.checkpoint import Checkpointer

            self.checkpointer = Checkpointer(
                checkpoint_dir, max_to_keep=checkpoint_keep
            )
            restored = self.checkpointer.restore(self.params)
            if restored is not None:
                # Manager restart resumes the federation (the reference
                # lost the global model here, SURVEY §5 checkpoint row).
                self.params = restored.params
                self.rounds.restore(
                    restored.meta.get("n_rounds", restored.step),
                    restored.meta.get("loss_history", []),
                )
        # the round in flight at crash time, recovered from the journal
        # and awaiting re-announce once the event loop is up
        self._recovered_round: Optional[dict] = None
        self._recovery_task = None
        if self.journal is not None:
            self._recover_from_journal(secure_agg)
        if self.ha_role == "active":
            # claim leadership: epoch strictly above anything the
            # journal has seen, fencing every prior incarnation
            self.ha_epoch = self._recovered_ha_epoch + 1
            self._ha_lease = replication.make_lease(
                self.ha_epoch, self.ha_replica_id, self.ha_lease_s
            )
            self.journal.append("ha_lease", **self._ha_lease)
            if self._ha_standbys:
                self._wal_shipper = replication.WalShipper(
                    name,
                    self.journal,
                    self._ha_standbys,
                    self.ha_replica_id,
                    lambda: self._session,
                    token=self.ha_token,
                    metrics=self.metrics,
                )
        self.allow_pickle = allow_pickle
        self.secure_agg = secure_agg
        self.secure_scale_bits = secure_scale_bits
        self.secure_phase_timeout = secure_phase_timeout
        self._rejection_logged_round: Optional[tuple] = None
        # live secure round: {"round_name", "cohort": [ids], "pks": {id: int}}
        self._secure_round: Optional[dict] = None
        self._secure_outboxes: Optional[dict] = None
        self._secure_task = None
        self._secure_finalizing = False
        self._checkpoint_task = None
        self._broadcasting = False
        self.simulator = None  # (FedSim, data, n_samples) triple when attached
        self._sim_args: Optional[dict] = None
        self._sim_task = None
        self.__session: Optional[aiohttp.ClientSession] = None
        self._register_handlers()
        self._background: list[PeriodicTask] = []
        if start_background_tasks:
            app.on_startup.append(self._start_background)
            app.on_cleanup.append(self._stop_background)

    # -- crash recovery ------------------------------------------------
    def _recover_from_journal(self, secure_agg: bool) -> None:
        """Replay snapshot+journal: rebuild membership (ids, keys,
        callback URLs) and the round counter, and stage any in-flight
        round for :meth:`_resume_round` once the event loop is up."""
        rec = self.journal.recover()
        self._recovered_ha_epoch = max(
            self._recovered_ha_epoch, rec.ha_epoch
        )
        if rec.empty:
            return
        for cid, c in rec.clients.items():
            self.registry.restore_client(
                cid,
                key=c.get("key"),
                remote=c.get("remote"),
                port=c.get("port"),
                url=c.get("url"),
                registered_at=c.get("registered_at"),
                num_updates=c.get("num_updates", 0),
                last_update=c.get("last_update"),
            )
        # the journal records every completed round (including the ones
        # the checkpoint's async save may not have landed before the
        # crash), so it is at least as new as the checkpoint — unless
        # journaling was enabled later, in which case keep the
        # checkpoint's counter/history
        if rec.n_rounds >= self.rounds.n_rounds:
            self.rounds.restore(rec.n_rounds, rec.loss_history)
        _log.info(
            "%s: recovered %d clients, %d completed rounds from journal",
            self.name, len(rec.clients), self.rounds.n_rounds,
        )
        if rec.open_round is None:
            return
        if self.recovery_policy == "abort" or secure_agg:
            # secure rounds can never resume: the mask/share directory
            # (self._secure_round) died with the process, so surviving
            # masked uploads could not be unmasked anyway
            reason = "secure_agg" if secure_agg else "recovery_policy"
            round_name = rec.open_round["round_name"]
            self.rounds._journal(
                "round_aborted", round_name=round_name, reason=reason,
            )
            self.metrics.inc("recovery_rounds_aborted")
            # the abort is an SLO event, not just a log line: land it in
            # rounds.jsonl and alerts.jsonl so a failover that kills a
            # secure round is auditable (secure mask/share state is
            # deliberately never shipped — forward secrecy over resume)
            self._finish_round_obs(round_name, f"aborted:recovery_{reason}")
            self.alerts.log_event({
                "event": "recovery_round_aborted",
                "round": round_name,
                "reason": reason,
                "ts": round(time.time(), 6),
            })
            _log.warning(
                "%s: in-flight round %s aborted on recovery (%s)",
                self.name, round_name, reason,
            )
            return
        self._recovered_round = rec.open_round

    async def _resume_round(self) -> None:
        """Re-announce the journal-recovered in-flight round to its
        surviving participants under its ORIGINAL name, so updates they
        trained before the crash (still parked in their outboxes,
        http_worker.py) land in the resumed round."""
        info = self._recovered_round
        self._recovered_round = None
        if info is None or self.rounds.in_progress:
            return
        round_name = info["round_name"]
        meta = dict(info.get("meta") or {})
        n_epoch = int(meta.get("n_epoch", DEFAULT_N_EPOCH))
        cohort = [
            cid for cid in sorted(info.get("participants") or [])
            if cid in self.registry
        ]
        if not cohort:
            self.rounds._journal(
                "round_aborted", round_name=round_name,
                reason="no surviving participants",
            )
            self.metrics.inc("recovery_rounds_aborted")
            _log.warning(
                "%s: round %s had no surviving participants; aborted",
                self.name, round_name,
            )
            return
        self.rounds.resume_round(round_name, **meta)
        self.metrics.inc("recovery_rounds_resumed")
        self._slo_base = self.metrics.snapshot()["counters"]
        trace_id = tracing.make_trace_id(self.name, round_name)
        _log.info(
            "%s: resuming round %s with %d participants",
            self.name, round_name, len(cohort),
        )
        # resumed broadcasts are always dense (never delta-encoded): the
        # quantization seed and blob anchor of the original broadcast
        # died with the old process, and a different anchor would
        # corrupt sparse-delta reconstruction
        state_dict = {
            k: np.ascontiguousarray(np.asarray(v))
            for k, v in params_to_state_dict(self.params).items()
        }
        self._broadcast_anchor_sd = state_dict
        with self._acc_lock:
            self._stream_acc = (
                self._new_stream_acc()
                if self.streaming_aggregation
                and self.aggregator[0] == "mean"
                else None
            )
        if self.allow_pickle:
            meta_out = {"update_name": round_name, "n_epoch": n_epoch}
            body = wire.encode_pickle(state_dict, meta_out)
            ctype = wire.PICKLE_CONTENT_TYPE
        else:
            envelope = self._publish_round_blobs(
                round_name, n_epoch, state_dict, None, None
            )
            body = json.dumps(envelope).encode()
            ctype = "application/json"
        payloads = dict(info.get("payloads") or {})
        rebroadcast = []
        self._broadcasting = True
        try:
            # journaled-payload replay FIRST: a participant whose
            # accepted update rode the WAL re-joins with its ORIGINAL
            # bytes re-ingested — zero re-training, zero retransfer.
            # Only participants with no journaled payload get the
            # re-announce below.
            for cid in cohort:
                p = payloads.get(cid)
                if not isinstance(p, dict) or not p.get("data"):
                    rebroadcast.append(cid)
                    continue
                try:
                    raw = base64.b64decode(p["data"])
                    self.rounds.client_start(cid)
                    resp = await self._ingest_update(
                        cid, raw, p.get("content_type")
                    )
                    ok = resp.status == 200
                except (asyncio.CancelledError, KeyboardInterrupt):
                    raise
                except Exception:
                    ok = False
                if ok:
                    self.metrics.inc("recovery_updates_reused")
                else:
                    self.metrics.inc("recovery_payload_replays_failed")
                    rebroadcast.append(cid)
            if rebroadcast:
                self.metrics.inc(
                    "recovery_rebroadcasts", float(len(rebroadcast))
                )
            # recovery re-announce is a span of the ORIGINAL round's
            # trace: the new incarnation's spans land in the same trace
            # id (derived from the round name), so an exported trace
            # shows both manager lifetimes and the recovery gap between
            with self.tracer.span(
                "recovery_rebroadcast",
                trace_id=trace_id,
                parent_id=tracing.root_span_id(trace_id),
                round=round_name,
                cohort=len(rebroadcast),
            ):
                await bounded_gather(
                    *[
                        self._notify_client(cid, body, ctype)
                        for cid in rebroadcast
                    ],
                    limit=self.fanout_concurrency,
                )
        finally:
            self._broadcasting = False
            # the reporting window starts NOW: the broadcast itself must
            # not count against the participants' round_timeout
            self.rounds.restart_clock()
        if self.rounds.in_progress and not len(self.rounds):
            started_wall = self.rounds.started_wall
            self.rounds.abort_round("resume broadcast unacknowledged")
            self.metrics.inc("recovery_rounds_aborted")
            self._finish_round_obs(
                round_name, "aborted:resume_unacknowledged",
                started_wall=started_wall,
            )
            return
        self._maybe_finish()

    # -- control-plane replication (server/replication.py) -------------
    async def _ha_tick(self) -> None:
        """One replication heartbeat. Active: renew + journal the lease,
        ship the WAL tail to every standby. Standby: promote once the
        active's lease has been expired past the grace window."""
        if self.ha_role == "active":
            self._ha_lease = replication.make_lease(
                self.ha_epoch, self.ha_replica_id, self.ha_lease_s
            )
            self.journal.append("ha_lease", **self._ha_lease)
            self.metrics.inc("ha_lease_renewals")
            if self._wal_shipper is not None:
                await self._wal_shipper.ship_once(
                    self.ha_epoch, self._ha_lease
                )
        elif self.ha_role == "standby" and self._wal_receiver is not None:
            if self.ha_auto_promote and self._wal_receiver.lease_expired(
                self.ha_promote_grace_s
            ):
                await self.promote()

    async def promote(self) -> bool:
        """Standby → active: stop accepting segments, replay the shipped
        WAL into live registry/round state, claim the next epoch, and
        start serving (resuming any in-flight round with its journaled
        payloads). Idempotent — a second call is a no-op."""
        if self.ha_role != "standby" or self._wal_receiver is None:
            return False
        receiver = self._wal_receiver
        # fence FIRST: from this instant every wal_segment POST from the
        # old active answers 409 stale_epoch, so nothing can mutate the
        # journal file underneath the replay below
        receiver.closed = True
        from baton_tpu.server.journal import Journal

        self.journal = Journal(self._journal_path, fsync=self._journal_fsync)
        self.registry.journal = self.journal
        self.rounds.journal = self.journal
        self._recover_from_journal(self.secure_agg)
        self.ha_epoch = (
            max(self._recovered_ha_epoch, receiver.epoch) + 1
        )
        self.ha_role = "active"
        self._ha_lease = replication.make_lease(
            self.ha_epoch, self.ha_replica_id, self.ha_lease_s
        )
        self.journal.append("ha_lease", **self._ha_lease)
        if self._ha_topology is not None:
            holder = (receiver.lease or {}).get("holder")
            if holder:
                self._ha_topology.mark_dead(str(holder))
            self._ha_topology.mark_alive(self.ha_replica_id)
        if self._ha_standbys:
            self._wal_shipper = replication.WalShipper(
                self.name,
                self.journal,
                self._ha_standbys,
                self.ha_replica_id,
                lambda: self._session,
                token=self.ha_token,
                metrics=self.metrics,
            )
        self.metrics.inc("ha_promotions")
        _log.warning(
            "%s: standby %s promoted to active at epoch %d "
            "(wal generation=%s applied_offset=%d)",
            self.name, self.ha_replica_id, self.ha_epoch,
            receiver.generation, receiver.offset,
        )
        if self._recovered_round is not None:
            await self._resume_round()
        return True

    def _standby_refusal(self) -> Optional[web.Response]:
        """503 for serving routes while this replica is a standby — the
        client's failover list (or the 307 topology) sends it to the
        active; a standby must never mutate round state."""
        if self.ha_role != "standby":
            return None
        return web.json_response(
            {"error": "Standby", "epoch": self.ha_epoch}, status=503
        )

    async def handle_wal_segment(self, request: web.Request) -> web.Response:
        """``POST /{name}/wal_segment`` — the replication ingress."""
        if self.ha_token and (
            request.headers.get(replication.HA_TOKEN_HEADER) != self.ha_token
        ):
            return web.json_response({"error": "Unauthorized"}, status=401)
        try:
            seg = await read_json_capped(request, self.max_upload_bytes)
        except BodyTooLarge:
            return web.json_response({"error": "Too Large"}, status=413)
        except (ValueError, TypeError):
            return web.json_response({"error": "Bad Segment"}, status=400)
        if not isinstance(seg, dict):
            return web.json_response({"error": "Bad Segment"}, status=400)
        if self._wal_receiver is not None and not self._wal_receiver.closed:
            status, body = self._wal_receiver.apply(seg)
            return web.json_response(body, status=status)
        # active (or promoted ex-standby): any segment at or below our
        # epoch is a zombie's — the 409 here is the split-brain fence
        try:
            seg_epoch = int(seg.get("epoch", 0))
        except (TypeError, ValueError):
            return web.json_response({"error": "Bad Segment"}, status=400)
        if seg_epoch <= self.ha_epoch:
            self.metrics.inc("wal_segments_refused_stale")
            return web.json_response(
                {"error": "stale_epoch", "epoch": self.ha_epoch}, status=409
            )
        return web.json_response({"error": "not_standby"}, status=409)

    async def handle_replication(self, request: web.Request) -> web.Response:
        """``GET /{name}/replication`` — role/epoch/WAL positions for
        the ops console's replication pane."""
        wal: dict = {}
        if self._wal_shipper is not None:
            wal = {
                "generation": self.journal.generation,
                "targets": self._wal_shipper.positions(),
                "min_shipped_offset": self._wal_shipper.min_shipped_offset(),
            }
        elif self._wal_receiver is not None:
            wal = self._wal_receiver.status()
        body = {
            "role": self.ha_role,
            "replica": self.ha_replica_id,
            "epoch": self.ha_epoch,
            "lease": (
                self._ha_lease
                if self.ha_role == "active"
                else (self._wal_receiver.lease if self._wal_receiver else None)
            ),
            "wal": wal,
            "topology": self._ha_replica_urls or None,
        }
        return web.json_response(json_clean(body))

    # ------------------------------------------------------------------
    async def _start_background(self, app=None) -> None:
        self._loop_probe.start()
        cull = PeriodicTask(self._cull_tick, max(self.registry.client_ttl / 2, 1))
        self._background = [cull.start()]
        if self.metrics_history_interval_s > 0:
            history = PeriodicTask(
                self._history_tick, self.metrics_history_interval_s
            )
            self._background.append(history.start())
        if self.rounds.round_timeout is not None:
            watchdog = PeriodicTask(
                self._watchdog_tick, max(self.rounds.round_timeout / 4, 0.25)
            )
            self._background.append(watchdog.start())
        if (
            (self.alerts.rules or self.runbooks.rules)
            and self.alerts_interval_s > 0
        ):
            alerts_task = PeriodicTask(
                self._alerts_tick, self.alerts_interval_s
            )
            self._background.append(alerts_task.start())
        if self.retention_interval_s > 0 and (
            self.tracer.spool_dir
            or (self.jsonl_max_bytes is not None
                and (self.rounds_log is not None or self.clients_log_path))
        ):
            retention = PeriodicTask(
                self._retention_tick, self.retention_interval_s
            )
            self._background.append(retention.start())
        if self.ha_role is not None:
            ha = PeriodicTask(
                self._ha_tick, max(self.ha_ship_interval_s, 0.05)
            )
            self._background.append(ha.start())
        if self._recovered_round is not None:
            self._recovery_task = asyncio.get_running_loop().create_task(
                self._resume_round()
            )

    async def _stop_background(self, app=None) -> None:
        self._loop_probe.stop()
        for task in self._background:
            await task.stop()
        if self._recovery_task is not None:
            await self._recovery_task
            self._recovery_task = None
        if self._secure_task is not None:
            await self._secure_task
            self._secure_task = None
        if self.__session is not None:
            await self.__session.close()
        if self._checkpoint_task is not None:
            await self._checkpoint_task
            self._checkpoint_task = None
        if self._ingest is not None:
            self._ingest.shutdown()
        if self.checkpointer is not None:
            self.checkpointer.close()
        if self.journal is not None:
            self.journal.close()

    async def _cull_tick(self) -> None:
        for cid in self.registry.cull():
            self.rounds.drop_client(cid)
            self.metrics.inc("clients_culled")
        self._maybe_finish()

    async def _history_tick(self) -> None:
        # record the DERIVED snapshot (registry/round/fleet gauges
        # included) so a history entry equals what /metrics would have
        # answered at that instant
        self.metrics.record_history(snapshot=self.metrics_snapshot())

    async def _alerts_tick(self) -> None:
        # advisory plane: any failure is logged and counted, never
        # propagated — same contract as the fleet ledger
        view: Optional[dict] = None
        try:
            view = obs_alerts.build_metric_view(
                self.metrics_snapshot(),
                list(self._recent_rounds),
                self.alerts.rounds_window,
            )
            self.alerts.evaluate(view, history=self.metrics.history())
        except Exception:
            self.metrics.inc("alerts_eval_errors")
            _log.exception("%s: alert evaluation tick failed", self.name)
        if not self.runbooks.rules:
            return
        # runbook plane rides the same tick: the runbook view is the
        # alert view plus the ledger's fleet.* classification metrics,
        # and alert-triggered rules follow the engine's firing set
        try:
            rb_view = dict(view or {})
            rb_view.update(
                obs_runbooks.derive_fleet_view(self.fleet.classify_all())
            )
            self.runbooks.evaluate(rb_view, firing=self.alerts.firing())
        except Exception:
            self.metrics.inc("runbooks_eval_errors")
            _log.exception("%s: runbook evaluation tick failed", self.name)

    async def _retention_tick(self) -> None:
        """Bound the on-disk observability artifacts: trace-spool GC
        (exempting traces that retained forensics bundles reference) and
        size-based rotation of ``rounds.jsonl`` / ``clients.jsonl``
        (their readers are torn-line-tolerant). All file IO off-loop."""
        if self.tracer.spool_dir:
            removed = await asyncio.to_thread(
                tracing.gc_spool,
                self.tracer.spool_dir,
                max_age_s=self.trace_spool_max_age_s,
                max_files=self.trace_spool_max_files,
                exempt=self.forensics.referenced_trace_ids(),
            )
            if removed:
                self.metrics.inc("trace_spool_gc_removed", removed)
        if self.jsonl_max_bytes is None:
            return
        if self.rounds_log is not None:
            if await asyncio.to_thread(
                self.rounds_log.maybe_rotate, self.jsonl_max_bytes
            ):
                self.metrics.inc("jsonl_rotations")
        if self.clients_log_path:
            if await asyncio.to_thread(
                maybe_rotate_jsonl, self.clients_log_path,
                self.jsonl_max_bytes,
            ):
                self.metrics.inc("jsonl_rotations")

    async def _watchdog_tick(self) -> None:
        if self._broadcasting:
            # round setup (secure phases + broadcast) is still running:
            # ending the round now would strand the in-flight broadcast
            # on a dead round_name — the same knife-edge class as the
            # cull-tick abort, one tick over. The straggler timeout is
            # for clients that fail to REPORT, and nobody has even been
            # notified yet.
            return
        if self.rounds.is_expired:
            self.end_round()  # partial aggregation of whoever reported

    @property
    def _session(self) -> aiohttp.ClientSession:
        if self.__session is None:
            self.__session = aiohttp.ClientSession()
        return self.__session

    # ------------------------------------------------------------------
    def _register_handlers(self) -> None:
        r = self.app.router
        r.add_get(f"/{self.name}/register", self.handle_register)
        r.add_get(f"/{self.name}/heartbeat", self.handle_heartbeat)
        r.add_get(f"/{self.name}/clients", self.handle_clients)
        r.add_get(f"/{self.name}/start_round", self.handle_start_round)
        r.add_get(f"/{self.name}/end_round", self.handle_end_round)
        r.add_get(f"/{self.name}/loss_history", self.handle_loss_history)
        r.add_post(f"/{self.name}/update", self.handle_update)
        # chunked resumable uplink: offset/total-framed PUTs + a GET
        # offset probe (aiohttp auto-answers HEAD for GET routes)
        r.add_put(
            f"/{self.name}/update_chunk/{{update_id}}",
            self.handle_update_chunk,
        )
        r.add_get(
            f"/{self.name}/update_chunk/{{update_id}}",
            self.handle_update_chunk_probe,
        )
        r.add_get(f"/{self.name}/metrics", self.handle_metrics)
        r.add_get(
            f"/{self.name}/metrics/history", self.handle_metrics_history
        )
        r.add_get(f"/{self.name}/fleet/health", self.handle_fleet_health)
        # alerting plane: rule states + firing/pending lists; forensics
        # bundles by content digest
        r.add_get(f"/{self.name}/alerts", self.handle_alerts)
        # runbook plane: rule states + per-rule actuation counts
        r.add_get(f"/{self.name}/runbooks", self.handle_runbooks)
        r.add_get(f"/{self.name}/forensics", self.handle_forensics_index)
        r.add_get(
            f"/{self.name}/forensics/{{digest}}", self.handle_forensics
        )
        r.add_get(
            f"/{self.name}/round_blob/{{digest}}", self.handle_round_blob
        )
        # distributed tracing: export one round's trace; ingest workers'
        # shipped spans into it
        r.add_get(
            f"/{self.name}/rounds/{{rid}}/trace", self.handle_round_trace
        )
        r.add_post(f"/{self.name}/trace_spans", self.handle_trace_spans)
        # control-plane replication: WAL ingress + status pane
        r.add_post(f"/{self.name}/wal_segment", self.handle_wal_segment)
        r.add_get(f"/{self.name}/replication", self.handle_replication)

    # -- v2 pull data plane --------------------------------------------
    _RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")

    async def handle_round_blob(self, request: web.Request) -> web.Response:
        """Serve an immutable round blob, with single-range resume.

        Only ``bytes=<start>-[<end>]`` ranges are honored (that is the
        resume shape); anything else is 416. The blob is immutable under
        its digest, so a resumed download never needs If-Range
        validation — the ETag IS the URL."""
        try:
            self.registry.verify(
                request.query.get("client_id", ""),
                request.query.get("key", ""),
            )
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Unauthorized"}, status=401)
        digest = request.match_info["digest"]
        entry = self._blobs.get(digest)
        if entry is None:
            # round rolled and retention dropped it — the worker falls
            # back to whatever the CURRENT round's envelope names
            return web.json_response({"err": "Unknown Blob"}, status=404)
        data, kind = entry
        total = len(data)
        headers = {"Accept-Ranges": "bytes", "ETag": f'"{digest}"'}
        status, start, end = 200, 0, total
        range_hdr = request.headers.get("Range")
        if range_hdr is not None:
            m = self._RANGE_RE.match(range_hdr.strip())
            if m:
                start = int(m.group(1))
                end = int(m.group(2)) + 1 if m.group(2) else total
            if not m or start >= end or end > total:
                headers["Content-Range"] = f"bytes */{total}"
                return web.Response(status=416, headers=headers)
            status = 206
            headers["Content-Range"] = f"bytes {start}-{end - 1}/{total}"
            if start > 0:
                self.metrics.inc("range_resumes")
        payload = data[start:end]
        self.metrics.inc("bytes_broadcast", len(payload))
        self.metrics.inc(
            "blob_hits_delta" if kind == "delta" else "blob_hits_full"
        )
        return web.Response(
            body=payload, status=status,
            content_type=wire.CONTENT_TYPE, headers=headers,
        )

    # -- membership ----------------------------------------------------
    async def handle_register(self, request: web.Request) -> web.Response:
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        client = self.registry.register(
            remote=request.remote, port=data.get("port"), url=data.get("url")
        )
        return web.json_response(
            {"client_id": client.client_id, "key": client.key}
        )

    async def handle_heartbeat(self, request: web.Request) -> web.Response:
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        try:
            self.registry.heartbeat(data.get("client_id"), data.get("key"))
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Invalid Client"}, status=401)
        # experiment sharding: a heartbeat landing on the wrong replica
        # learns the owner lazily — 307 + the refreshed topology map
        # (the data the worker needs to retarget every other call too)
        if self._ha_topology is not None:
            owner = self._ha_topology.assign(self.name)
            if owner is not None and owner != self.ha_replica_id:
                url = self._ha_replica_urls.get(owner)
                if url:
                    self.metrics.inc("heartbeats_redirected")
                    return web.json_response(
                        {
                            "url": f"{url}/{self.name}/",
                            "replica": owner,
                            "topology": self._ha_replica_urls,
                        },
                        status=307,
                        headers={"Location": f"{url}/{self.name}/heartbeat"},
                    )
        return web.json_response("OK")

    async def handle_clients(self, request: web.Request) -> web.Response:
        return web.json_response(self.registry.to_json())

    # -- rounds --------------------------------------------------------
    async def handle_start_round(self, request: web.Request) -> web.Response:
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        try:
            n_epoch = int(request.query["n_epoch"])
        except KeyError:
            n_epoch = DEFAULT_N_EPOCH
        except ValueError:
            return web.json_response({"err": "Invalid Epoch Value"}, status=400)
        try:
            status = await self.start_round(n_epoch)
        except RoundInProgress:
            return web.json_response(
                {"err": "Update already in progress"}, status=423
            )
        return web.json_response(status)

    async def handle_end_round(self, request: web.Request) -> web.Response:
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        if self._secure_round is not None:
            await self._end_round_secure()
        else:
            self.end_round()
        return web.json_response(json_clean(self.round_state()))

    async def handle_loss_history(self, request: web.Request) -> web.Response:
        return web.json_response([float(x) for x in self.rounds.loss_history])

    def metrics_snapshot(self) -> dict:
        """The full metrics snapshot — counters, gauges, histogram
        timers (p50/p95/p99), plus the derived registry/round gauges.
        This is the ONE producer behind both ``GET /{name}/metrics`` and
        the loadgen SLO evaluator (:mod:`baton_tpu.loadgen.slo`), so the
        scraped view and the gated view cannot drift."""
        from baton_tpu.server import secure

        # advisory fleet classification gauges (fleet_clients_*) are
        # published into the registry so scrapes AND history entries
        # carry them
        self.fleet.export_gauges(self.metrics)
        snap = self.metrics.snapshot()
        snap["gauges"]["clients_registered"] = float(len(self.registry))
        snap["gauges"]["rounds_completed"] = float(self.rounds.n_rounds)
        snap["gauges"]["round_in_progress"] = float(self.rounds.in_progress)
        dh = secure.dh_cache_stats()
        snap["gauges"]["dh_cache_size"] = float(dh["size"])
        snap["gauges"]["dh_cache_hits"] = float(dh["hits"])
        snap["gauges"]["dh_cache_misses"] = float(dh["misses"])
        if self.ha_role is not None:
            g = snap["gauges"]
            g["replication_epoch"] = float(self.ha_epoch)
            g["replication_role_active"] = float(self.ha_role == "active")
            g["replication_standbys"] = float(len(self._ha_standbys))
            if self._wal_shipper is not None:
                g["replication_wal_shipped_offset"] = float(
                    self._wal_shipper.min_shipped_offset()
                )
            recv = self._wal_receiver
            if recv is not None and self.ha_role == "standby":
                g["replication_wal_applied_offset"] = float(recv.offset)
                lag = recv.lag_s()
                if lag is not None:
                    g["replication_wal_lag_s"] = float(lag)
            lease = (
                self._ha_lease
                if self.ha_role == "active"
                else (recv.lease if recv is not None else None)
            )
            if isinstance(lease, dict):
                try:
                    g["replication_lease_remaining_s"] = float(
                        lease.get("expires", 0.0)
                    ) - time.time()
                except (TypeError, ValueError):
                    pass
        return snap

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.json_response(self.metrics_snapshot())

    async def handle_metrics_history(
        self, request: web.Request
    ) -> web.Response:
        """``GET /{name}/metrics/history`` — the timestamped snapshot
        ring (oldest first) recorded by the background history task.
        ``?since=<ts>`` returns only samples strictly newer than the
        given wall-clock timestamp, so pollers (the ops console) fetch
        deltas instead of the full ring every refresh."""
        since = None
        raw_since = request.query.get("since")
        if raw_since is not None:
            try:
                since = float(raw_since)
            except ValueError:
                return web.json_response(
                    {"err": "Bad since Timestamp"}, status=400
                )
        history = self.metrics.history(since=since)
        return web.json_response({
            "interval_s": self.metrics_history_interval_s,
            "samples": len(history),
            "history": history,
        })

    async def handle_fleet_health(
        self, request: web.Request
    ) -> web.Response:
        """``GET /{name}/fleet/health`` — per-client telemetry windows
        + advisory anomaly classifications (server/fleet.py)."""
        return web.json_response(json_clean(self.fleet.health_snapshot()))

    # -- alerting plane ------------------------------------------------
    async def handle_alerts(self, request: web.Request) -> web.Response:
        """``GET /{name}/alerts`` — every rule's lifecycle state, last
        value, and recent transitions, plus the firing/pending lists."""
        return web.json_response(json_clean(self.alerts.status_snapshot()))

    # -- runbook plane -------------------------------------------------
    async def handle_runbooks(self, request: web.Request) -> web.Response:
        """``GET /{name}/runbooks`` — every remediation rule's state,
        trigger, params, and how often the manager applied it."""
        return web.json_response(
            json_clean(self.runbooks.status_snapshot())
        )

    def _record_actuation(self, act: dict, detail: dict) -> None:
        """One applied remediation → the round's explainability record
        (``rounds.jsonl`` ``actuations`` entry names the rule AND its
        triggering alert/classification) + the engine's counter."""
        entry = {
            "action": act["action"],
            "rule": act["rule"],
            "trigger": act["trigger"],
            "value": act.get("value"),
            "detail": detail,
        }
        self._pending_actuations.append(entry)
        self.runbooks.record_actuation(act["rule"])

    def _apply_round_deadline(self) -> None:
        """``adaptive_deadline`` actuation: fit THIS round's reporting
        deadline from the fleet's observed per-client ``train_s``
        medians instead of the static ``round_timeout``. Advisory —
        requires a configured ``round_timeout`` (that is what starts
        the expiry watchdog) and any failure keeps the static value."""
        try:
            act = self.runbooks.actuation("adaptive_deadline")
            if act is None or self.rounds.round_timeout is None:
                return
            p = act["params"]
            classified = self.fleet.classify_all()
            max_s = p.get("max_s")
            if max_s is None:
                # bound a bad fit: never hold a round open past 4x the
                # operator's static timeout
                max_s = 4.0 * self.rounds.round_timeout
            deadline = obs_runbooks.fit_deadline(
                (c.get("train_s_median") for c in classified.values()),
                quantile=p["quantile"],
                margin=p["margin"],
                min_s=p.get("min_s"),
                max_s=max_s,
            )
            if deadline is None:
                return
            self.rounds.set_deadline(deadline)
            self._record_actuation(act, {
                "deadline_s": round(deadline, 6),
                "base_timeout_s": self.rounds.round_timeout,
                "clients_fit": sum(
                    1 for c in classified.values()
                    if c.get("train_s_median") is not None
                ),
            })
        except Exception:
            self.metrics.inc("runbooks_eval_errors")
            _log.exception(
                "%s: adaptive_deadline actuation failed", self.name
            )

    def _fedbuff_buffer_full(self) -> bool:
        """``fedbuff_fallback`` actuation: under churn, finish the
        round as soon as a FedBuff-style buffer of
        ``ceil(buffer_frac · cohort)`` reports has landed instead of
        waiting out the stragglers."""
        try:
            act = self.runbooks.actuation("fedbuff_fallback")
            if act is None:
                return False
            cohort = len(self.rounds.clients)
            need = max(1, math.ceil(act["params"]["buffer_frac"] * cohort))
            have = len(self.rounds.client_responses)
            if have < need:
                return False
            self._record_actuation(act, {
                "buffered": have,
                "required": need,
                "cohort": cohort,
                "cut_stragglers": sorted(
                    set(self.rounds.clients)
                    - set(self.rounds.client_responses)
                ),
            })
            return True
        except Exception:
            self.metrics.inc("runbooks_eval_errors")
            return False

    async def handle_forensics_index(
        self, request: web.Request
    ) -> web.Response:
        return web.json_response(
            json_clean({"bundles": self.forensics.list_bundles()})
        )

    async def handle_forensics(self, request: web.Request) -> web.Response:
        """``GET /{name}/forensics/{digest}`` — one content-addressed
        bundle manifest with its evidence sections inline."""
        bundle = self.forensics.get(request.match_info["digest"])
        if bundle is None:
            return web.json_response({"err": "Unknown Bundle"}, status=404)
        return web.json_response(json_clean(bundle))

    def _arm_forensics(self, rule, event: dict) -> None:
        """``on_capture`` hook: a capture-flagged rule fired — arm a
        bundle for the next finished round, and arm the one-shot
        ``jax.profiler`` capture that the next training step consumes
        (graceful no-op off-TPU / when no step runs while armed)."""
        base = self.forensics.dir_path or tempfile.gettempdir()
        profile_dir = os.path.join(
            base, f"forensics_profile_{self.name}_{rule.name}"
        )
        profiling.arm_forensics_trace(profile_dir)
        self._forensics_armed = {
            "rule": rule.name,
            "severity": rule.severity,
            "armed_ts": float(event.get("ts") or time.time()),
            "profile_dir": profile_dir,
        }

    def _build_forensics_bundle(self, record: dict) -> None:
        """Package the armed capture against the round that just
        finished: every evidence section present or null-with-reason
        (:mod:`baton_tpu.obs.forensics`)."""
        armed, self._forensics_armed = self._forensics_armed, None
        if armed is None:
            return
        sections: Dict[str, Any] = {}
        reasons: Dict[str, str] = {}
        snap = self.metrics.snapshot()
        sections["jax_profile"] = obs_forensics.profile_dir_summary(
            armed.get("profile_dir")
        )
        if sections["jax_profile"] is None:
            reasons["jax_profile"] = (
                "profiler produced no artifacts (off-TPU no-op, or no "
                "training step ran while armed)"
            )
        try:
            sections["task_stacks"] = obs_forensics.dump_asyncio_tasks()
        except Exception as exc:
            reasons["task_stacks"] = obs_forensics.safe_repr_exc(exc)
        lag = (snap.get("timers") or {}).get("loop_lag_s")
        if lag is not None:
            sections["loop_lag"] = lag
        try:
            stragglers = record.get("stragglers") or None
            sections["fleet_slice"] = self.fleet.health_slice(stragglers)
        except Exception as exc:
            reasons["fleet_slice"] = obs_forensics.safe_repr_exc(exc)
        trace_id = record.get("trace_id")
        try:
            export = self.tracer.export(trace_id) if trace_id else None
            if export and export.get("traceEvents"):
                sections["round_trace"] = export
        except Exception as exc:
            reasons["round_trace"] = obs_forensics.safe_repr_exc(exc)
        history = self.metrics.history()
        if history:
            sections["metric_history"] = history[-32:]
        manifest = obs_forensics.build_manifest(
            rule=armed["rule"],
            severity=armed["severity"],
            round_name=record.get("round"),
            trace_id=trace_id,
            node="manager",
            armed_ts=armed["armed_ts"],
            captured_ts=time.time(),
            sections=sections,
            reasons=reasons,
        )
        digest = self.forensics.put(manifest)
        self.metrics.inc("alerts_captures_built")
        self.alerts.log_event({
            "ts": round(time.time(), 6),
            "event": "forensics",
            "rule": armed["rule"],
            "severity": armed["severity"],
            "round": record.get("round"),
            "digest": digest,
            "sections_present": manifest["sections_present"],
        })
        _log.info(
            "%s: forensics bundle %s captured for rule %s (round %s)",
            self.name, digest, armed["rule"], record.get("round"),
        )

    # -- distributed tracing -------------------------------------------
    def _round_trace_id(self, rid: str) -> str:
        """A trace id from either a full round name or a bare round
        index (``7`` → ``update_{name}_00007``)."""
        round_name = (
            f"update_{self.name}_{int(rid):05d}" if rid.isdigit() else rid
        )
        return tracing.make_trace_id(self.name, round_name)

    async def handle_round_trace(self, request: web.Request) -> web.Response:
        """``GET /{name}/rounds/{rid}/trace`` → Chrome ``trace_event``
        JSON for one round (load it straight into Perfetto). ``rid`` is
        the round name or its numeric index. Export reads the crash
        spool, so it runs off-loop."""
        trace_id = self._round_trace_id(request.match_info["rid"])
        export = await asyncio.to_thread(self.tracer.export, trace_id)
        if not export["traceEvents"]:
            return web.json_response({"err": "Unknown Trace"}, status=404)
        return web.json_response(export)

    async def handle_trace_spans(self, request: web.Request) -> web.Response:
        """``POST /{name}/trace_spans`` — authenticated span upstream:
        workers ship their finished spans here after delivering an
        update, so one endpoint serves the whole distributed trace."""
        try:
            self.registry.verify(
                request.query.get("client_id", ""),
                request.query.get("key", ""),
            )
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Unauthorized"}, status=401)
        try:
            data = await read_json_capped(request)
        except BodyTooLarge as exc:
            self.metrics.inc("control_rejected_413")
            return web.json_response(
                {"err": "Body Too Large", "limit_bytes": exc.limit},
                status=413,
            )
        spans = data.get("spans") if isinstance(data, dict) else data
        if not isinstance(spans, list):
            return web.json_response({"err": "Bad Span List"}, status=400)
        # ingest validates per-span and appends to the crash spool —
        # file writes, so keep it off the loop
        accepted = await asyncio.to_thread(self.tracer.ingest, spans)
        self.metrics.inc("trace_spans_ingested", accepted)
        if accepted < len(spans):
            self.metrics.inc("trace_spans_rejected", len(spans) - accepted)
        return web.json_response({"accepted": accepted})

    def _finish_round_obs(
        self,
        round_name: str,
        outcome: str,
        participants=(),
        responses: Optional[dict] = None,
        started_wall: Optional[float] = None,
    ) -> None:
        """Round-end observability: emit the round's ROOT span
        retroactively (deterministic span id — phase spans were already
        parent-linked to it) and append the SLO record to rounds.jsonl.
        Called from every path that finishes or aborts a round."""
        trace_id = tracing.make_trace_id(self.name, round_name)
        end = time.time()
        t0 = started_wall if started_wall is not None else end
        self.tracer.record_span(
            "round",
            trace_id=trace_id,
            span_id=tracing.root_span_id(trace_id),
            start=t0,
            end=end,
            round=round_name,
            outcome=outcome,
        )
        # fold the round into the fleet ledger FIRST (rounds_log may be
        # off): every cohort member gets a reported/straggler/missed
        # observation, and non-reporters get a classification-backed
        # "why" for the SLO record. Advisory plane — it must never be
        # able to break round completion.
        cohort, self._round_cohort = self._round_cohort, []
        try:
            straggler_why = self.fleet.record_round(
                round_name, cohort, participants, responses
            )
        except Exception:
            _log.exception("%s: fleet ledger record failed", self.name)
            straggler_why = {}
        responses = responses or {}
        participants = sorted(participants)
        reporters = sorted(responses)
        base = self._slo_base or {}
        self._slo_base = None
        counters = self.metrics.snapshot()["counters"]
        deltas = {
            k: v - base.get(k, 0.0)
            for k, v in counters.items()
            if v != base.get(k, 0.0)
        }
        phases = {
            s["name"]: round(s["end"] - s["start"], 6)
            for s in self.tracer.spans_for(trace_id)
            if s.get("service") == self.tracer.service
            and s.get("name") != "round"
        }
        # compute plane: fold the reporters' per-client compute records
        # (obs/compute.py) into one round section — every unmeasured
        # aggregate is null-with-reason, never a bare null — and export
        # the latest round's values as gauges for /metrics + the console
        compute_section = obs_compute.summarize_round(
            [r.get("compute") for r in responses.values()
             if isinstance(r, dict)]
        )
        for gauge, key in (
            ("compute_mfu", "mfu"),
            ("compute_samples_per_sec_per_chip", "samples_per_sec_per_chip"),
            ("compute_peak_hbm_gb", "peak_hbm_gb"),
            ("compute_steps", "steps"),
        ):
            val = compute_section.get(key)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                self.metrics.set_gauge(gauge, float(val))
        self.metrics.set_gauge(
            "compute_reporters", compute_section["reporters"]
        )
        self.metrics.set_gauge(
            "compute_recompile_storm",
            1.0 if compute_section.get("recompile_storms") else 0.0,
        )
        cs = compute_section.get("compile_s")
        if isinstance(cs, (int, float)) and not isinstance(cs, bool):
            # root-side compile histogram (worst reporter per round) with
            # this round's trace as the exemplar: a p99 compile spike on
            # /metrics links straight to the round that recompiled
            self.metrics.observe(
                "compute_compile_s", float(cs),
                exemplar=(trace_id, tracing.root_span_id(trace_id)),
            )
        record = {
            "round": round_name,
            "round_index": self.rounds.n_rounds,
            "trace_id": trace_id,
            "service": self.tracer.service,
            "outcome": outcome,
            "duration_s": round(end - t0, 6),
            "participants": len(participants),
            "reporters": len(reporters),
            "stragglers": [c for c in participants if c not in responses],
            "straggler_why": straggler_why,
            "bytes_uploaded": deltas.get("bytes_uploaded", 0.0),
            "bytes_broadcast": deltas.get("bytes_broadcast", 0.0),
            "counters_delta": deltas,
            "phase_s": phases,
            "compute": compute_section,
        }
        # explainability contract: every remediation the manager applied
        # during this round lands in the round's own record, naming the
        # rule AND the alert/classification that triggered it
        acts, self._pending_actuations = self._pending_actuations, []
        if acts:
            record["actuations"] = acts
        # mirrored for the alert evaluator's rounds.* tail (no file IO
        # on an evaluation tick) — kept even when rounds_log is off
        self._recent_rounds.append(record)
        if self.rounds_log is not None:
            self.rounds_log.append(record)
        if self._forensics_armed is not None:
            # forensics is advisory: a broken capture must never break
            # round completion (same contract as the fleet ledger)
            try:
                self._build_forensics_bundle(record)
            except Exception:
                self.metrics.inc("alerts_eval_errors")
                _log.exception(
                    "%s: forensics bundle capture failed", self.name
                )

    def _new_stream_acc(self):
        """The round's streaming accumulator: sequential (deterministic)
        by default, sharded partials under ``fold_shards>1``."""
        if self.fold_shards > 1:
            return agg.ShardedStreamingMean(self.fold_shards)
        return agg.StreamingMean()

    def _retry_after_s(self) -> float:
        return self._ingest.retry_after_s if self._ingest is not None else 1.0

    def _reject_429(self, msg: str) -> web.Response:
        self.metrics.inc("ingest_rejected_429")
        return web.json_response(
            {"err": msg}, status=429,
            headers={"Retry-After": f"{self._retry_after_s():g}"},
        )

    async def handle_update(self, request: web.Request) -> web.Response:
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        try:
            client_id = self.registry.verify(
                request.query.get("client_id", ""), request.query.get("key", "")
            )
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Unauthorized"}, status=401)
        t_read0 = time.monotonic()
        try:
            body = await read_body_capped(request, self.max_upload_bytes)
        except BodyTooLarge:
            self.metrics.inc("uploads_rejected_413")
            return web.json_response({"err": "Payload Too Large"}, status=413)
        # server-side view of the upload wall time (body streaming in):
        # the bandwidth denominator the ledger records per client
        upload_s = time.monotonic() - t_read0
        self.metrics.inc("bytes_uploaded", len(body))
        ctx = tracing.parse_traceparent(request.headers.get("traceparent"))
        if ctx is None:
            return await self._ingest_update(
                client_id, body, request.content_type, upload_s=upload_s
            )
        # join the caller's trace: the worker's upload span is the parent
        with self.tracer.span(
            "ingest", trace_id=ctx[0], parent_id=ctx[1],
            client=client_id, bytes=len(body),
        ):
            return await self._ingest_update(
                client_id, body, request.content_type, upload_s=upload_s
            )

    def _make_upload_decoder(self, body: bytes, content_type):
        """Build the decode+validate closure the ingest pipeline runs on
        a pool thread. Pure CPU work over immutable inputs — no loop
        state is touched off-loop (the anchor hint is captured here, on
        the loop; validation only needs the model's shapes, which are
        round-independent)."""
        anchor_hint = self._broadcast_anchor_sd

        def decode():
            tensors, meta = wire.decode_any(
                body, content_type, allow_pickle=self.allow_pickle
            )
            # validate at the door: a missing/mis-shaped tensor must be
            # rejected now, not crash aggregation after the round state
            # is consumed (which would discard every client's work)
            # coerce meta fields HERE so a malformed n_samples/
            # loss_history 400s at the door instead of 500ing later
            meta_n_samples = float(meta.get("n_samples", 0))
            meta_losses = [float(x) for x in meta.get("loss_history", [])]
            update_id = (
                str(meta["update_id"]) if meta.get("update_id") else None
            )
            compressed = False
            if meta.get("edge_partial") is not None:
                # an edge aggregator's cohort partial: always a dense
                # template-shaped mean (the edge refuses masked uploads
                # and decompresses before folding). Shape-validate like
                # a plain update; the secure/streaming 409s live in
                # _ingest_edge_partial where they can be counted.
                if not isinstance(meta["edge_partial"], dict):
                    raise _BadUpload("Bad Edge Partial")
                if meta.get("compressed") or meta.get("secure"):
                    raise _BadUpload(
                        "Edge Partial Cannot Be Compressed Or Masked"
                    )
                state_dict_to_params(self.params, tensors)
            elif meta.get("compressed"):
                if self.secure_agg:
                    # a sparse support set leaks which coordinates moved;
                    # masking needs dense ring elements (ops/compression.py)
                    raise _BadUpload("Compressed Upload In Secure Round")
                scheme = (meta["compressed"] or {}).get("scheme") \
                    if isinstance(meta["compressed"], dict) else None
                if scheme != "topk":
                    # an unknown scheme decoded under top-k semantics
                    # would poison the aggregate; reject precisely
                    raise _BadUpload(f"Unknown Compression Scheme {scheme!r}")
                compressed = True
                anchor = (
                    anchor_hint
                    if anchor_hint is not None
                    else params_to_state_dict(self.params)
                )
                self._validate_compressed_upload(tensors, anchor)
            elif self.secure_agg:
                self._validate_masked_upload(tensors, meta)
            else:
                state_dict_to_params(self.params, tensors)
            return tensors, meta, meta_n_samples, meta_losses, update_id, \
                compressed

        return decode

    def _journal_payload(
        self, client_id: str, round_name: str, body: bytes, content_type
    ) -> None:
        """Journal an accepted update's wire bytes so a successor (same
        process restarted, or a promoted standby replaying shipped WAL)
        can re-ingest the update instead of asking the worker to
        re-train: the worker's one-slot outbox dropped the payload on
        our 200 ack, so the journal is the ONLY copy that survives us.
        Called at the acceptance point, after ``client_end`` journaled
        ``update_accepted`` — replay pairs the two."""
        if (
            self.journal is None
            or not self.journal_payloads
            or not body
        ):
            return
        if (
            self.journal_payload_max_bytes is not None
            and len(body) > self.journal_payload_max_bytes
        ):
            self.metrics.inc("journal_payloads_skipped_large")
            return
        self.journal.append(
            "update_payload",
            round_name=round_name,
            client_id=client_id,
            content_type=str(content_type or "application/octet-stream"),
            data=base64.b64encode(body).decode("ascii"),
        )
        self.metrics.inc("journal_payloads_journaled")

    async def _ingest_update(
        self,
        client_id: str,
        body: bytes,
        content_type,
        upload_s: Optional[float] = None,
    ) -> web.Response:
        """Accept one assembled upload body (single POST or completed
        chunk session): decode/validate off-loop, then run the round
        checks + acceptance bookkeeping loop-atomically, then fold.

        The acceptance-point invariant from PR 2 holds: once the 200 is
        sent, the update counts — so all bookkeeping happens with no
        intervening await, and the off-loop fold this handler awaits
        before answering is guaranteed to land in the round mean
        (``end_round`` additionally drains the fold lanes)."""
        decode = self._make_upload_decoder(body, content_type)
        pipe = self._ingest
        try:
            if pipe is not None:
                fut = pipe.submit_decode(decode)
                if fut is None:
                    return self._reject_429("Ingest Queue Full")
                decoded = await fut
            else:
                decoded = decode()
        except _BadUpload as e:
            return web.json_response({"err": e.msg}, status=400)
        except (MemoryError, asyncio.CancelledError):
            # resource exhaustion / shutdown are NOT client errors: let
            # them propagate (500 / cancellation) instead of masking
            # them as "Bad Payload" and silently inviting a retry
            raise
        except Exception:
            return web.json_response({"err": "Bad Payload"}, status=400)
        tensors, meta, meta_n_samples, meta_losses, update_id, compressed = \
            decoded
        round_name = meta.get("update_name")
        if not self.rounds.in_progress or round_name != self.rounds.round_name:
            return web.json_response({"error": "Wrong Update"}, status=410)
        if self._secure_finalizing:
            # dropout recovery has started and this client's masks are
            # being cancelled as dropped — its late upload can no longer
            # be folded into the sum
            return web.json_response({"error": "Round Finalizing"}, status=410)
        if isinstance(meta.get("edge_partial"), dict):
            # a cohort partial from an edge aggregator — the edge itself
            # is not a round participant, so this must branch before the
            # cohort/participant 410s below
            return await self._ingest_edge_partial(
                client_id, tensors, meta, update_id
            )
        if (
            self._secure_round is not None
            and client_id not in self._secure_round["cohort"]
        ):
            # not in this round's cohort: its masks reference a pk
            # directory nobody else holds (e.g. a straggler from an
            # aborted attempt that reuses this round name) — folding it
            # in would add uncancellable mask noise
            return web.json_response({"error": "Not In Cohort"}, status=410)
        if client_id not in self.rounds.clients:
            # never client_start'ed this round: an unsampled registered
            # client (cohort_fraction < 1) or a straggler from an aborted
            # attempt reusing the round name. Deliberate deviation from
            # the reference (which records any authenticated upload,
            # manager.py:105-107): counting an outsider would skew the
            # mean AND trip clients_left to 0 early, ending the round
            # before sampled participants report.
            return web.json_response(
                {"error": "Not A Participant"}, status=410
            )
        if (
            update_id is not None
            and self.rounds.update_ids.get(client_id) == update_id
        ):
            # the worker's at-least-once outbox retried an upload whose
            # first delivery DID land (e.g. the 200 was lost in transit).
            # Ack idempotently without re-counting: folding it in twice
            # would double this client's sample weight in the aggregate.
            self.metrics.inc("duplicate_updates_deduped")
            return web.json_response("OK")
        if (
            client_id in self.rounds.client_responses
            or client_id in self._accepting
        ):
            # a DIFFERENT update from a client whose first update was
            # already accepted (or is mid-acceptance across the buffered
            # path's decompress await): the first accepted update per
            # client per round is FINAL — its 200 ack promised it
            # counts, and under streaming aggregation it is already
            # folded into the running sum and cannot be retracted.
            self.metrics.inc("repeat_updates_ignored")
            return web.json_response("OK")
        # the per-round anchor (set once in start_round; what clients
        # loaded). Read AFTER the 410s: stale uploads never reach it.
        anchor = (
            self._broadcast_anchor_sd
            if self._broadcast_anchor_sd is not None
            else params_to_state_dict(self.params)
        )
        response = {
            "masked": bool(meta.get("secure", False)),
            "n_samples": meta_n_samples,
            "loss_history": meta_losses,
            "update_id": update_id,
            "upload_bytes": len(body),
        }
        # worker self-reported timings piggybacked on the update meta
        # (train wall time, heartbeat RTT) + the server-measured upload
        # wall: the fleet ledger's per-client observation fields
        timings = _clean_timings(meta.get("timings")) or {}
        if upload_s is not None and upload_s > 0:
            timings["upload_s"] = round(upload_s, 6)
        if timings:
            response["timings"] = timings
        # per-round compute record (obs/compute.py): sanitized at the
        # door, folded into the round's SLO record + fleet ledger
        compute = _clean_compute(meta.get("compute"))
        if compute is not None:
            response["compute"] = compute
        elif meta.get("compute") is not None:
            self.metrics.inc("compute_records_invalid")
        acc = self._stream_acc
        if acc is not None and not response["masked"]:
            # streaming FedAvg: acceptance bookkeeping FIRST (no await
            # between the checks above and client_end — loop-atomic, so
            # a racing duplicate sees client_responses), then the
            # decompress+fold runs off-loop on this shard's fold lane.
            # Awaiting it before the 200 keeps the old contract: after
            # any ack, the update IS in the running sum. Restrict to the
            # anchor's keys so a surplus tensor in an upload cannot
            # enter the running sums.
            response["streamed"] = True
            self.rounds.client_end(client_id, response)
            self._journal_payload(client_id, round_name, body, content_type)
            self.registry.record_update(client_id, round_name)
            self.metrics.inc("updates_received")
            if compressed:
                self.metrics.inc("compressed_updates_received")
            shard = 0
            if self.fold_shards > 1:
                shard = self._fold_rr % self.fold_shards
                self._fold_rr += 1
            sharded = self.fold_shards > 1

            def fold():
                t = tensors
                if compressed:
                    t = self._decompress_upload(t, anchor)
                payload = {k: t[k] for k in anchor}
                # decompress ran lock-free above (pure); only the fold
                # into the shared accumulator needs _acc_lock — the
                # loop-side simulated cohort add()s into the same one
                with self._acc_lock:
                    if sharded:
                        acc.add(payload, meta_n_samples, shard=shard)
                    else:
                        acc.add(payload, meta_n_samples)

            if pipe is not None:
                await pipe.submit_fold(shard, fold)
            else:
                fold()
            del tensors
            self._maybe_finish()
            return web.json_response("OK")
        # buffered / masked path: tensors are retained until end_round
        if compressed:
            # reconstruct AFTER the round checks: the anchor is only
            # right for the current round; stale uploads were already
            # 410'd above. The decompress runs off-loop, so the client
            # is flagged mid-acceptance for duplicate suppression and
            # the round checks re-run after the await.
            self._accepting.add(client_id)
            try:
                if pipe is not None:
                    tensors = await pipe.run_unbounded(
                        lambda: self._decompress_upload(tensors, anchor)
                    )
                else:
                    tensors = self._decompress_upload(tensors, anchor)
            finally:
                self._accepting.discard(client_id)
            if (
                not self.rounds.in_progress
                or round_name != self.rounds.round_name
            ):
                return web.json_response({"error": "Wrong Update"}, status=410)
            if client_id not in self.rounds.clients:
                return web.json_response(
                    {"error": "Not A Participant"}, status=410
                )
            self.metrics.inc("compressed_updates_received")
        response["state_dict"] = tensors
        del tensors
        self.rounds.client_end(client_id, response)
        if not response["masked"]:
            # masked (secure-agg) bodies are useless to a successor —
            # the mask directory dies with this process (see the
            # recovery abort policy) — so only plaintext payloads ship
            self._journal_payload(client_id, round_name, body, content_type)
        self.registry.record_update(client_id, round_name)
        self.metrics.inc("updates_received")
        self._maybe_finish()
        return web.json_response("OK")

    async def _ingest_edge_partial(
        self, client_id: str, tensors: dict, meta: dict, update_id
    ) -> web.Response:
        """Merge one edge aggregator's cohort partial into the round.

        The partial's tensors are the weighted mean over the edge's
        cohort and ``edge_partial.contributors`` maps each worker to its
        ``{n_samples, update_id, loss_history}``. Folding the mean back
        with the summed weight reproduces the flat sequential fold
        exactly (``StreamingMean`` is associative: ``mean × Σw`` is the
        cohort's weighted sum), and crediting each contributor its own
        per-worker response keeps loss-history aggregation, round
        accounting, and worker dedup (a direct retry after a lost edge
        ack) identical to the flat topology.

        Refusals are 409s the edge/worker can act on: secure rounds need
        direct uploads (a partial-folded masked update would break
        unmasking — the masks only cancel in the full cohort sum), and
        non-streaming aggregators need individual updates."""
        round_name = meta.get("update_name")
        info = meta["edge_partial"]
        edge_name = str(info.get("edge") or client_id)
        if self.secure_agg or self._secure_round is not None:
            self.metrics.inc("updates_refused_edge_secure")
            return web.json_response(
                {"err": "Secure Round Requires Direct Uploads"}, status=409
            )
        acc = self._stream_acc
        if acc is None:
            # buffered/robust aggregators need the individual updates
            self.metrics.inc("updates_refused_edge_unsupported")
            return web.json_response(
                {"err": "Edge Partials Require Streaming Aggregation"},
                status=409,
            )
        if update_id is not None and (
            (client_id, update_id) in self._edge_partial_ids
        ):
            self.metrics.inc("duplicate_updates_deduped")
            return web.json_response("OK")
        contributors = info.get("contributors")
        if not isinstance(contributors, dict) or not contributors:
            return web.json_response({"err": "Bad Edge Partial"}, status=400)
        credited, total_w = [], 0.0
        try:
            parsed = [
                (
                    str(cid),
                    float(c.get("n_samples", 0)),
                    str(c["update_id"]) if c.get("update_id") else None,
                    [float(x) for x in (c.get("loss_history") or [])],
                    int(c.get("bytes") or 0),
                    _clean_timings(c.get("timings")),
                    _clean_compute(c.get("compute")),
                )
                for cid, c in sorted(contributors.items())
            ]
        except (AttributeError, TypeError, ValueError):
            return web.json_response({"err": "Bad Edge Partial"}, status=400)
        for cid, w, uid, losses, nbytes, timings, compute in parsed:
            if not (w > 0) or not math.isfinite(w):
                return web.json_response(
                    {"err": "Bad Edge Partial"}, status=400
                )
            # the weight ALWAYS counts toward the fold — it physically
            # backs the partial's mean — but credit is conditional
            total_w += w
            if cid not in self.rounds.clients:
                # unsampled (cohort_fraction < 1) or dropped mid-round
                self.metrics.inc("edge_contributors_unknown")
                continue
            if cid in self.rounds.client_responses or cid in self._accepting:
                # the worker also delivered direct (edge ack lost →
                # direct retry race): its contribution is inside the
                # partial's mean and cannot be subtracted, so the weight
                # folds but the credit stays with the direct delivery
                self.metrics.inc("edge_contributor_conflicts")
                continue
            credited.append((cid, w, uid, losses, nbytes, timings, compute))
        if total_w <= 0:
            return web.json_response({"err": "Bad Edge Partial"}, status=400)
        # edge-tier phase wall times ride the partial's meta: folded
        # into float counters so each round's counters_delta (and thus
        # rounds.jsonl) shows where edge time went this round
        phase_s = info.get("phase_s")
        if isinstance(phase_s, dict):
            for key, counter in (
                ("fold", "edge_phase_fold_s"),
                ("blob_fetch", "edge_phase_blob_fetch_s"),
                ("settle", "edge_phase_settle_s"),
                ("ship_prev", "edge_phase_ship_prev_s"),
            ):
                val = phase_s.get(key)
                if (
                    isinstance(val, (int, float))
                    and not isinstance(val, bool)
                    and math.isfinite(val)
                    and val >= 0
                ):
                    self.metrics.inc(counter, float(val))
        anchor = (
            self._broadcast_anchor_sd
            if self._broadcast_anchor_sd is not None
            else params_to_state_dict(self.params)
        )
        # acceptance bookkeeping loop-atomically BEFORE the awaited fold
        # (same contract as the direct streaming path): once the 200 is
        # sent every credited contributor counts, and a racing duplicate
        # — partial or direct — sees client_responses/_edge_partial_ids
        if update_id is not None:
            self._edge_partial_ids.add((client_id, update_id))
        for cid, w, uid, losses, nbytes, timings, compute in credited:
            resp = {
                "masked": False,
                "n_samples": w,
                "loss_history": losses,
                "update_id": uid,
                "streamed": True,
                "via_edge": edge_name,
            }
            if nbytes > 0:
                resp["upload_bytes"] = nbytes
            if timings:
                resp["timings"] = timings
            if compute is not None:
                resp["compute"] = compute
            self.rounds.client_end(cid, resp)
            self.registry.record_update(cid, round_name)
            self.metrics.inc("updates_received")
            self.metrics.inc("edge_contributors_credited")
        self.metrics.inc("updates_received_edge_partial")
        shard = 0
        if self.fold_shards > 1:
            shard = self._fold_rr % self.fold_shards
            self._fold_rr += 1
        sharded = self.fold_shards > 1
        fold_w = total_w

        def fold():
            payload = {k: tensors[k] for k in anchor}
            if sharded:
                acc.add(payload, fold_w, shard=shard)
            else:
                acc.add(payload, fold_w)

        pipe = self._ingest
        if pipe is not None:
            await pipe.submit_fold(shard, fold)
        else:
            fold()
        self._maybe_finish()
        return web.json_response("OK")

    # -- chunked resumable uplink --------------------------------------
    async def handle_update_chunk(self, request: web.Request) -> web.Response:
        """``PUT /{name}/update_chunk/{update_id}?offset=&total=``.

        Chunks append strictly at the committed offset; a mismatched
        ``offset`` answers ``409 {"offset": committed}`` and the worker
        resumes from there (the manager is authoritative). The final
        chunk's response IS the update's acceptance response — 200 means
        accepted exactly as a single POST would have been."""
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        try:
            client_id = self.registry.verify(
                request.query.get("client_id", ""), request.query.get("key", "")
            )
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Unauthorized"}, status=401)
        update_id = request.match_info["update_id"]
        try:
            offset = int(request.query["offset"])
            total = int(request.query["total"])
        except (KeyError, ValueError):
            return web.json_response({"err": "Bad Chunk Framing"}, status=400)
        if total <= 0 or offset < 0 or offset > total:
            return web.json_response({"err": "Bad Chunk Framing"}, status=400)
        if self.max_upload_bytes is not None and total > self.max_upload_bytes:
            # declared-size admission: reject the whole upload on its
            # FIRST chunk, before buffering anything
            self.metrics.inc("uploads_rejected_413")
            return web.json_response({"err": "Payload Too Large"}, status=413)
        key = (client_id, update_id)
        sess = self._chunks.get(key)
        if sess is None:
            if offset != 0:
                # unknown session (evicted, or a probe raced a round
                # roll): the committed offset is 0 — start over
                return web.json_response(
                    {"err": "Unknown Chunk Session", "offset": 0}, status=409
                )
            if len(self._chunks) >= self.max_chunk_sessions:
                return self._reject_429("Too Many Chunk Sessions")
            sess = ChunkSession(
                client_id=client_id, update_id=update_id, total=total,
                spill_dir=self.chunk_spill_dir,
            )
            self._chunks[key] = sess
            self.metrics.set_gauge("chunk_sessions_active", len(self._chunks))
        if sess.total != total:
            # inconsistent framing poisons the session — drop it
            self._chunks.pop(key, None)
            sess.discard()
            self.metrics.set_gauge("chunk_sessions_active", len(self._chunks))
            return web.json_response({"err": "Inconsistent Total"}, status=400)
        if sess.busy:
            # a zombie retry racing its own live transfer must not
            # interleave bytes into the buffer
            return web.json_response(
                {"err": "Chunk In Flight", "offset": sess.offset}, status=409
            )
        if offset != sess.offset:
            return web.json_response(
                {"err": "Offset Mismatch", "offset": sess.offset}, status=409
            )
        sess.busy = True
        try:
            try:
                chunk = await read_body_capped(request, sess.total - offset)
            except BodyTooLarge:
                self.metrics.inc("uploads_rejected_413")
                return web.json_response(
                    {"err": "Chunk Overruns Total"}, status=413
                )
            if offset == 0 and len(chunk) >= 4 and not self.allow_pickle \
                    and not wire.is_btw1(chunk):
                # first-frame sniff: don't buffer max_upload_bytes of a
                # payload that is destined for "Bad Payload" anyway
                self._chunks.pop(key, None)
                sess.discard()
                self.metrics.set_gauge(
                    "chunk_sessions_active", len(self._chunks))
                return web.json_response({"err": "Bad Payload"}, status=400)
            sess.extend(chunk)
            self.metrics.inc("bytes_uploaded", len(chunk))
            self.metrics.inc("chunk_bytes_received", len(chunk))
            if sess.offset < sess.total:
                return web.json_response({"offset": sess.offset})
            ctx = tracing.parse_traceparent(
                request.headers.get("traceparent")
            )
            if ctx is None:
                resp = await self._ingest_update(
                    client_id, sess.payload(), wire.CONTENT_TYPE
                )
            else:
                # the FINAL chunk's traceparent parents the assembly
                # ingest — one span per assembled upload, not per chunk
                with self.tracer.span(
                    "ingest", trace_id=ctx[0], parent_id=ctx[1],
                    client=client_id, bytes=sess.total, chunked=True,
                ):
                    resp = await self._ingest_update(
                        client_id, sess.payload(), wire.CONTENT_TYPE
                    )
        finally:
            sess.busy = False
        if resp.status == 429:
            # ingest queue full at assembly: keep the session — the
            # retry re-sends only the (empty) final frame, not 100 MB
            return resp
        self._chunks.pop(key, None)
        sess.discard()
        self.metrics.set_gauge("chunk_sessions_active", len(self._chunks))
        if resp.status == 200:
            self.metrics.inc("chunked_uploads_assembled")
        return resp

    async def handle_update_chunk_probe(
        self, request: web.Request
    ) -> web.Response:
        """Committed-offset probe (GET; aiohttp serves HEAD from the
        same route). An unknown session reports offset 0 — "start
        over" and "never started" are the same instruction."""
        try:
            client_id = self.registry.verify(
                request.query.get("client_id", ""), request.query.get("key", "")
            )
        except (UnknownClient, AuthError):
            return web.json_response({"err": "Unauthorized"}, status=401)
        sess = self._chunks.get((client_id, request.match_info["update_id"]))
        offset = sess.offset if sess is not None else 0
        return web.json_response(
            {"offset": offset, "total": sess.total if sess else None},
            headers={"Upload-Offset": str(offset)},
        )

    def _validate_compressed_upload(self, tensors, anchor) -> None:
        """Structural check for a "<name>@idx"/"<name>@val" sparse-delta
        upload (ops/compression.py wire layout): every model tensor
        present, indices int / unique / in range, val/idx lengths equal,
        any "@scale" a single finite value. Everything that could crash
        or poison :meth:`_decompress_upload` is rejected HERE (400), not
        after the round state is consumed."""
        for k, ref in anchor.items():
            idx = np.asarray(tensors[f"{k}@idx"])
            val = np.asarray(tensors[f"{k}@val"])
            size = int(np.size(np.asarray(ref)))
            if idx.ndim != 1 or val.shape != idx.shape:
                raise ValueError(f"bad sparse layout for {k}")
            if not np.issubdtype(idx.dtype, np.integer):
                raise ValueError(f"non-integer indices for {k}")
            if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= size):
                raise ValueError(f"index out of range for {k}")
            if np.unique(idx).size != idx.size:
                # duplicate indices silently drop delta mass in the
                # scatter (dense[idx] = val keeps only the last write)
                raise ValueError(f"duplicate indices for {k}")
            if not np.all(np.isfinite(np.asarray(val, np.float64))):
                raise ValueError(f"non-finite values for {k}")
            if f"{k}@scale" in tensors:
                scale = np.asarray(tensors[f"{k}@scale"]).ravel()
                if scale.size != 1 or not np.isfinite(scale[0]):
                    raise ValueError(f"bad scale for {k}")
                # a finite-but-huge scale can overflow val*scale to inf
                # in float32 and poison the aggregate past this door
                if val.size and not np.all(np.isfinite(
                    np.asarray(val, np.float32) * np.float32(scale[0])
                )):
                    raise ValueError(f"scale overflow for {k}")

    def _decompress_upload(self, tensors, anchor) -> dict:
        """anchor + sparse delta -> dense state_dict (float32)."""
        out = {}
        for k, ref in anchor.items():
            idx = np.asarray(tensors[f"{k}@idx"])
            val = np.asarray(tensors[f"{k}@val"], np.float32)
            if f"{k}@scale" in tensors:
                val = val * float(np.asarray(tensors[f"{k}@scale"]).ravel()[0])
            ref = np.asarray(ref, np.float32)
            dense = np.zeros(ref.size, np.float32)
            dense[idx] = val
            out[k] = ref + dense.reshape(ref.shape)
        return out

    # ------------------------------------------------------------------
    def attach_simulator(self, sim, data, n_samples, wave_size=None) -> None:
        """Let a TPU-simulated cohort participate in every HTTP round as
        one aggregate client (weight = its total sample count)."""
        if self.secure_agg:
            raise ValueError(
                "a simulated cohort runs inside the aggregator process — "
                "masking it from the server it lives in is meaningless; "
                "use plain aggregation for simulation"
            )
        self.simulator = sim
        self._sim_args = {
            "data": data,
            "n_samples": jnp.asarray(n_samples),
            "wave_size": wave_size,
        }

    async def start_round(self, n_epoch: int) -> Dict[str, bool]:
        round_name = self.rounds.start_round(n_epoch=n_epoch)
        self._slo_base = self.metrics.snapshot()["counters"]
        # actuations applied while THIS round runs; _finish_round_obs
        # moves them into the round's rounds.jsonl record
        self._pending_actuations = []
        self._apply_round_deadline()
        trace_id = tracing.make_trace_id(self.name, round_name)
        self._secure_round = None  # invalidate any stale secure state
        # chunk sessions are per-round: a body assembled for the dead
        # round would only 410 at ingest, so drop the buffers now
        self._chunks.clear()
        self.metrics.set_gauge("chunk_sessions_active", 0)
        self._fold_rr = 0
        self._edge_partial_ids.clear()
        # _broadcasting must cover the WHOLE round setup — the secure
        # key/share phases included, not just the notify fan-out:
        # participants are only recorded at broadcast time, so a cull
        # tick firing during a long pre-broadcast phase sees
        # len(rounds)==0 and aborts a healthy round. Observed: EVERY
        # C=256 secure round died exactly this way (the O(C^2) share
        # phase outlasts the ttl/2=150 s cull period; C=128's ~135 s
        # phase just squeaked under — another knife edge).
        self._broadcasting = True
        try:
            # all setup-phase spans hang off the round's deterministic
            # root span id; the root itself is emitted retroactively at
            # round end (_finish_round_obs)
            with self.tracer.span(
                "round_setup",
                trace_id=trace_id,
                parent_id=tracing.root_span_id(trace_id),
                round=round_name,
            ):
                result = await self._start_round_phases(round_name, n_epoch)
        finally:
            self._broadcasting = False
            # round setup (secure phases + notify fan-out) is the
            # manager's own time; the expiry clock times the
            # participants' reporting window, which opens here
            self.rounds.restart_clock()
        # every participant may have reported during the (deferred)
        # broadcast window — settle the round now that the guard is down
        self._maybe_finish()
        return result

    async def _start_round_phases(
        self, round_name: str, n_epoch: int
    ) -> Dict[str, bool]:
        started_wall = self.rounds.started_wall
        for cid in self.registry.cull():
            self.rounds.drop_client(cid)
        if not len(self.registry) and self.simulator is None:
            # Fix of SURVEY §2.9 item 3: abort releases the round.
            self.rounds.abort_round()
            self._finish_round_obs(
                round_name, "aborted:no_clients", started_wall=started_wall
            )
            return {}
        # streaming FedAvg: created BEFORE any notify so a fast worker's
        # upload (which can land mid-broadcast) has somewhere to fold.
        # Robust aggregators are order statistics over the whole cohort
        # and secure rounds only ever yield a masked SUM — both keep the
        # buffered path (self._stream_acc stays None).
        with self._acc_lock:
            self._stream_acc = (
                self._new_stream_acc()
                if self.streaming_aggregation
                and self.aggregator[0] == "mean"
                and not self.secure_agg
                else None
            )
        state_dict = params_to_state_dict(self.params)
        meta = {"update_name": round_name, "n_epoch": n_epoch}
        # pin_shapes actuation: ask the cohort to hold batch/sequence
        # shapes fixed for this round (workers that predate the key
        # ignore it — the envelope parser reads only known fields)
        try:
            _pin_act = self.runbooks.actuation("pin_shapes")
        except Exception:
            _pin_act = None
        if _pin_act is not None:
            meta["pin_shapes"] = True
            self._record_actuation(_pin_act, {"pin_shapes": True})
        encoding = None
        delta_tensors = None
        if self.broadcast_quantize_bits is not None:
            from baton_tpu.ops.compression import (
                dequantize_state_dict,
                quantize_state_dict,
            )

            bits = self.broadcast_quantize_bits
            state_dict = {
                k: np.asarray(v)
                for k, v in quantize_state_dict(
                    state_dict, seed=self.rounds.n_rounds, bits=bits
                ).items()
            }
            meta["quantized"] = {"bits": bits}
            encoding = {"quantized": {"bits": bits}}
            # sparse uplink deltas are computed against what the clients
            # actually LOADED — the dequantized broadcast ROUND-TRIPPED
            # through the model's param dtypes (state_dict_to_params
            # casts each leaf; skipping that cast would leave the anchor
            # off by an ulp per coordinate for non-f32 params)
            self._broadcast_anchor_sd = params_to_state_dict(
                state_dict_to_params(
                    self.params, dequantize_state_dict(state_dict)
                )
            )
        else:
            state_dict = {
                k: np.ascontiguousarray(np.asarray(v))
                for k, v in state_dict.items()
            }
            if self._delta_spec is not None and self._prev_blob_sd is not None:
                from baton_tpu.ops.compression import (
                    apply_delta_state_dict,
                    delta_encode_state_dict,
                )

                # the round's broadcast is DEFINED as the reconstruction
                # anchor + delta (bit-identical numpy on both sides) so
                # anchored workers and full-blob workers load the exact
                # same tensors — the worker verifies by re-encoding its
                # reconstruction and hashing it against the blob digest
                delta_tensors = delta_encode_state_dict(
                    self._prev_blob_sd, state_dict, self._delta_spec,
                    seed=self.rounds.n_rounds,
                )
                state_dict = apply_delta_state_dict(
                    self._prev_blob_sd, delta_tensors
                )
            # materialize the round anchor ONCE here, not per upload:
            # self.params is invariant until end_round, and a per-upload
            # params_to_state_dict is a full-model device-to-host copy
            self._broadcast_anchor_sd = state_dict
        cohort_ids = self._sample_cohort()
        # remember the fan-out for the fleet ledger: a sampled client
        # that never acks/reports is a "missed" observation at round end
        self._round_cohort = list(cohort_ids)
        if self.secure_agg:
            # Bonawitz round 0 (AdvertiseKeys): per-round DH key
            # agreement. Clients that fail are excluded BEFORE the pk
            # directory circulates.
            with self.tracer.span("secure_keys", cohort=len(cohort_ids)):
                pk_results = await bounded_gather(
                    *[
                        self._collect_pk(cid, round_name)
                        for cid in cohort_ids
                    ],
                    limit=self.fanout_concurrency,
                )
            pks = {cid: p for cid, p in pk_results if p is not None}
            if not pks:
                # observable abort: a silent {} return made a whole
                # cohort's failure look like "workers never responded"
                # (C=256 postmortem, CHANGES_r5.md)
                self.metrics.inc("secure_rounds_aborted_keys")
                _log.warning(
                    "%s: secure round aborted — no member advertised "
                    "keys (cohort %d)", self.name, len(cohort_ids))
                self.rounds.abort_round()
                self._finish_round_obs(
                    round_name, "aborted:secure_keys",
                    started_wall=started_wall,
                )
                return {}
            cohort_a = sorted(pks)
            t = len(cohort_a) // 2 + 1  # honest majority threshold
            # Bonawitz round 1 (ShareKeys): every member Shamir-shares
            # its self-mask seed and mask key across the cohort; the
            # sealed boxes are relayed (opaque to this server) inside
            # the round_start broadcast. Members that fail here never
            # distributed shares, so nobody may mask toward them — the
            # masking cohort is exactly the successful sharers.
            with self.tracer.span("secure_shares", cohort=len(cohort_a)):
                share_results = await bounded_gather(
                    *[
                        self._collect_shares(cid, round_name, pks, t)
                        for cid in cohort_a
                    ],
                    limit=self.fanout_concurrency,
                )
            outboxes = {cid: m for cid, m in share_results if m is not None}
            cohort = sorted(outboxes)
            if len(cohort) < t:
                # fewer sharers than the reconstruction threshold: the
                # round could never be unmasked — abort before training
                self.metrics.inc("secure_rounds_aborted_shares")
                _log.warning(
                    "%s: secure round aborted — %d/%d members completed "
                    "ShareKeys, below threshold t=%d (phase budget %.0fs)",
                    self.name, len(cohort), len(cohort_a), t,
                    self._secure_phase_budget_s())
                self.rounds.abort_round()
                self._finish_round_obs(
                    round_name, "aborted:secure_shares",
                    started_wall=started_wall,
                )
                return {}
            self._secure_round = {
                "round_name": round_name,
                "cohort": cohort,
                "index": {cid: x + 1 for x, cid in enumerate(cohort_a)},
                "t": t,
                "c_pks": {cid: p[0] for cid, p in pks.items()},
                "scale_bits": self.secure_scale_bits,
                # validation template cached once per round: per-upload
                # params_to_state_dict would device-to-host copy the full
                # model C times per round just to read names/shapes
                "template_shapes": {
                    k: tuple(v.shape)
                    for k, v in params_to_state_dict(self.params).items()
                },
            }
            meta["secure"] = {
                "cohort": cohort,
                "scale_bits": self.secure_scale_bits,
                # inbox is per-recipient — filled in the broadcast loop
            }
            self._secure_outboxes = outboxes
        # Participation is recorded inside _notify_client the moment a
        # client acks — NOT after the gather. A fast worker can train and
        # upload before slower notifies finish; recording late would let
        # its update hit a round that doesn't know it (the reference has
        # this exact race, manager.py:87-89). _broadcasting additionally
        # keeps _maybe_finish from ending/aborting the round while acks
        # are still arriving.
        if self.allow_pickle:
            # Reference-protocol PUSH broadcast (manager.py:77-86): stock
            # reference workers can only decode pickled state_dicts, so
            # an allow_pickle experiment speaks pickle in BOTH directions
            # — uploads were already accepted via wire.decode_any.
            body = wire.encode_pickle(state_dict, meta)
            coros = [
                self._notify_client(cid, body, wire.PICKLE_CONTENT_TYPE)
                for cid in cohort_ids
            ]
        else:
            # v2 PULL broadcast: serialize the round params ONCE into a
            # content-addressed blob; notify bodies are tiny envelopes.
            envelope = self._publish_round_blobs(
                round_name, n_epoch, state_dict, delta_tensors, encoding
            )
            if meta.get("pin_shapes"):
                envelope["pin_shapes"] = True
            if self._secure_round is not None:
                # per-recipient envelopes: each cohort member's carries
                # ITS inbox of sealed share boxes from the others
                recipients = self._secure_round["cohort"]
                outboxes = self._secure_outboxes
                coros = []
                for cid in recipients:
                    inbox = {
                        sender: outboxes[sender].get(cid)
                        for sender in recipients
                        if sender != cid and outboxes[sender].get(cid)
                    }
                    env = dict(envelope)
                    env["secure"] = dict(meta["secure"], inbox=inbox)
                    coros.append(self._notify_client(
                        cid, json.dumps(env).encode(), "application/json"
                    ))
            else:
                # ONE shared body for the whole cohort — no per-recipient
                # dict: a 1024-cohort round holds one reference
                shared = json.dumps(envelope).encode()
                coros = [
                    self._notify_client(cid, shared, "application/json")
                    for cid in cohort_ids
                ]
        with self.tracer.span("broadcast", cohort=len(coros)):
            results = await bounded_gather(
                *coros, limit=self.fanout_concurrency
            )

        if self.simulator is not None:
            self.rounds.client_start("__simulated__")
            self._sim_task = asyncio.get_running_loop().create_task(
                self._run_simulated(round_name, n_epoch)
            )
            # the cohort is a participant like any other: report its ack
            # in the start_round response (reference manager.py:87-89
            # records acknowledging clients)
            results = list(results) + [("__simulated__", True)]

        if self.rounds.in_progress and not len(self.rounds):
            self.rounds.abort_round()
            self._secure_round = None
            self._finish_round_obs(
                round_name, "aborted:broadcast_unacknowledged",
                started_wall=started_wall,
            )
        return dict(results)

    def _publish_round_blobs(
        self, round_name, n_epoch, state_dict, delta_tensors, encoding
    ) -> dict:
        """Encode the round's tensors ONCE into the blob store and build
        the v2 notify envelope. Retention keeps exactly this round's
        full blob, the previous full blob (a straggler may still be
        mid-download when the round rolls), and the last
        ``delta_chain_depth`` rounds of delta blobs (the chain)."""
        full_blob = wire.encode(state_dict, {})
        full_digest = self._blobs.put(full_blob, kind="full")
        envelope: Dict[str, Any] = {
            "v": 2,
            "update_name": round_name,
            "n_epoch": n_epoch,
            "blob": {"digest": full_digest, "size": len(full_blob)},
        }
        if encoding is not None:
            envelope["encoding"] = encoding
        keep = [full_digest, self._prev_blob_digest]
        hops = self._delta_hops
        if delta_tensors is not None and full_digest != self._prev_blob_digest:
            delta_blob = wire.encode(delta_tensors, {})
            delta_digest = self._blobs.put(delta_blob, kind="delta")
            hop = {
                "digest": delta_digest,
                "size": len(delta_blob),
                "from": self._prev_blob_digest,
                "to": full_digest,
            }
            envelope["delta"] = {
                k: hop[k] for k in ("digest", "size", "from")
            }
            # depth-N delta chain: the retained consecutive hops still
            # link into this round's anchor, so a worker anchored k
            # rounds back (k < delta_chain_depth) chains anchor → ... →
            # N through k small delta pulls instead of a full one. Each
            # hop's reconstruction is digest-verified against its "to"
            # — every hop is bit-defined the same way the depth-1
            # delta is. A discontinuity (recovery, an encoding round)
            # restarts the chain at this hop.
            if not (hops and hops[-1]["to"] == hop["from"]):
                hops = []
            hops = (hops + [hop])[-self.delta_chain_depth:]
            if len(hops) >= 2:
                envelope["delta_chain"] = [dict(h) for h in hops]
        elif not (
            delta_tensors is None
            and full_digest == self._prev_blob_digest
            and hops
            and hops[-1]["to"] == full_digest
        ):
            hops = []
        else:
            # params didn't move this round: the retained hops still
            # end at this round's blob, so workers anchored up to
            # delta_chain_depth rounds back keep their delta paths —
            # offer the last hop directly and the chain unchanged
            envelope["delta"] = {
                k: hops[-1][k] for k in ("digest", "size", "from")
            }
            if len(hops) >= 2:
                envelope["delta_chain"] = [dict(h) for h in hops]
        keep.extend(h["digest"] for h in hops)
        self._blobs.retain(keep)
        if encoding is None:
            # dense broadcasts anchor the next round's delta; quantized
            # ones don't (their tensors are @q layouts the delta path
            # doesn't speak, and the stochastic seed changes per round)
            self._prev_blob_sd = state_dict
            self._prev_blob_digest = full_digest
            self._delta_hops = hops
        else:
            self._prev_blob_sd = None
            self._prev_blob_digest = None
            self._delta_hops = []
        return envelope

    def _sample_cohort(self) -> list:
        """The round's notification cohort: all registered clients at
        ``cohort_fraction=1`` (reference behavior), else a uniform sample
        of ``max(min_cohort, fraction * N)`` without replacement.

        With runbook rules loaded, cohort selection is routed through
        :meth:`_sample_cohort_runbooks`, which applies any active
        ``pin_shapes`` quarantine / ``overprovision`` / ``bias_cohort``
        actuations; a failure there falls back to this uniform path so a
        runbook bug can never stop rounds from forming."""
        if self.runbooks.rules:
            try:
                return self._sample_cohort_runbooks(
                    list(self.registry.clients)
                )
            except Exception:
                self.metrics.inc("runbooks_eval_errors")
                _log.exception(
                    "%s: runbook cohort selection failed — falling back "
                    "to uniform sampling", self.name,
                )
        ids = list(self.registry.clients)
        if self.cohort_fraction >= 1.0 or len(ids) <= self.min_cohort:
            return ids
        k = min(len(ids), max(self.min_cohort,
                              int(round(self.cohort_fraction * len(ids)))))
        return sorted(self._cohort_rng.sample(ids, k))

    def _sample_cohort_runbooks(self, ids: list) -> list:
        """Cohort selection under active runbook actuations.

        Order matters and is part of the explainability contract:
        ``pin_shapes`` first narrows eligibility (quarantine clients
        whose recent windows carried recompile storms), then
        ``overprovision`` inflates the invite count against expected
        misses, then ``bias_cohort`` reweights the draw AWAY from
        slow/flaky clients without ever hard-excluding them — every
        client keeps a nonzero weight, so the fairness floor holds."""
        classified = self.fleet.classify_all()
        eligible = list(ids)

        act = self.runbooks.actuation("pin_shapes")
        if act is not None and act["params"].get("quarantine"):
            offenders = {
                cid for cid in eligible
                if classified.get(cid, {}).get("storms")
            }
            kept = [cid for cid in eligible if cid not in offenders]
            # never quarantine the round away: only narrow eligibility
            # while a viable cohort remains
            if offenders and len(kept) >= self.min_cohort:
                eligible = kept
                self._record_actuation(act, {
                    "quarantined": sorted(offenders),
                    "eligible": len(eligible),
                })

        if self.cohort_fraction >= 1.0 or len(eligible) <= self.min_cohort:
            return sorted(eligible) if eligible is not ids else eligible
        base_k = min(
            len(eligible),
            max(self.min_cohort,
                int(round(self.cohort_fraction * len(eligible)))),
        )
        k = base_k

        act = self.runbooks.actuation("overprovision")
        if act is not None and base_k < len(eligible):
            p = act["params"]
            k, eps = obs_runbooks.overprovision_count(
                base_k, len(eligible), float(act.get("value") or 0.0),
                epsilon_max=p["epsilon_max"], gain=p["gain"],
            )
            if k > base_k:
                self._record_actuation(act, {
                    "base_k": base_k,
                    "k": k,
                    "epsilon": round(eps, 6),
                    "miss_rate": act.get("value"),
                })

        if k >= len(eligible):
            return sorted(eligible)

        act = self.runbooks.actuation("bias_cohort")
        if act is not None:
            p = act["params"]
            downweight = set(p["statuses"])
            weights = {
                cid: p["weight"]
                for cid in eligible
                if classified.get(cid, {}).get("status") in downweight
            }
            if weights:
                picked = obs_runbooks.weighted_sample(
                    eligible, weights, k, self._cohort_rng
                )
                self._record_actuation(act, {
                    "weight": p["weight"],
                    "downweighted": len(weights),
                    "k": k,
                })
                return sorted(picked)

        return sorted(self._cohort_rng.sample(eligible, k))

    def _secure_phase_budget_s(self) -> float:
        """Per-request timeout for the secure-protocol phases. The
        ShareKeys phase is O(C) 2048-bit modexps PER MEMBER (O(C^2)
        total) — a fixed budget that is generous at 64 members starves
        the whole cohort at 256 (observed: aiohttp's default 300 s
        total timeout vs ~540 s of aggregate box building in the
        one-process benchmark topology), so the default scales with
        registry size. ``secure_phase_timeout`` overrides."""
        if self.secure_phase_timeout is not None:
            return self.secure_phase_timeout
        return max(300.0, 3.0 * max(1, len(self.registry)))

    async def _secure_post(self, client_id: str, endpoint: str, payload: dict):
        """POST a secure-protocol message to one worker; None on any
        failure (the protocol tolerates per-member failures by cohort
        exclusion or share-threshold slack)."""
        try:
            client = self.registry[client_id]
        except UnknownClient:
            return None  # culled between snapshot and task run
        url = (
            f"{client.url.rstrip('/')}/{endpoint}"
            f"?client_id={client_id}&key={client.key}"
        )
        try:
            async with self._session.post(
                url, json=payload, headers=trace_headers(),
                timeout=aiohttp.ClientTimeout(
                    total=self._secure_phase_budget_s()),
            ) as resp:
                if resp.status == 200:
                    return await resp.json()
                if resp.status == 404:
                    self.registry.drop(client_id)
                # 409/410 etc.: alive but unavailable for this round
        except asyncio.TimeoutError:
            # alive but too slow for this phase: cohort exclusion, NOT
            # eviction — and never let the bare TimeoutError (which is
            # not an aiohttp.ClientError) escape into the phase gather,
            # where it would 500 the whole start_round
            return None
        except (aiohttp.ClientError, ValueError, KeyError):
            self.registry.drop(client_id)
        return None

    async def _collect_pk(self, client_id: str, round_name: str):
        """AdvertiseKeys with one client → (cid, (c_pk, s_pk) | None).

        Degenerate public keys (0/1/p−1 — a Byzantine member's subgroup
        confinement) are rejected HERE so they never enter the directory:
        forwarded, they would make every honest worker's share-sealing
        loop fail and kill the whole cohort every round."""
        from baton_tpu.server import secure

        data = await self._secure_post(
            client_id, "secure_keys", {"round": round_name}
        )
        try:
            c_pk, s_pk = int(data["c_pk"], 16), int(data["s_pk"], 16)
            if not (1 < c_pk < secure.MODP_P - 1):
                return client_id, None
            if not (1 < s_pk < secure.MODP_P - 1):
                return client_id, None
            return client_id, (c_pk, s_pk)
        except (TypeError, KeyError, ValueError):
            return client_id, None

    async def _collect_shares(
        self, client_id: str, round_name: str, pks: dict, t: int
    ):
        """ShareKeys with one client → (cid, {recipient: sealed_hex})."""
        data = await self._secure_post(
            client_id,
            "secure_shares",
            {
                "round": round_name,
                "pks": {
                    cid: {"c": f"{c:x}", "s": f"{s:x}"}
                    for cid, (c, s) in pks.items()
                },
                "t": t,
            },
        )
        try:
            return client_id, {
                str(k): str(v) for k, v in data["shares"].items()
            }
        except (TypeError, KeyError, AttributeError):
            return client_id, None

    async def _request_unmask(
        self, client_id: str, round_name: str, survivors, dropped,
        c_pk: int,
    ):
        """Unmasking with one reporter → its share bundle or None.

        ``c_pk`` (the reporter's own advertised mask public key) binds
        the request to ONE key-generation instance: aborted rounds
        reuse their name, so a stale finalizer's delayed unmask could
        otherwise pin its partition onto a same-name replacement
        round's state — the worker refuses on key mismatch."""
        return await self._secure_post(
            client_id,
            "secure_unmask",
            {"round": round_name, "survivors": survivors,
             "dropped": dropped, "c_pk": f"{c_pk:x}"},
        )

    async def _notify_client(
        self, client_id: str, body: bytes, content_type: str = wire.CONTENT_TYPE
    ):
        try:
            client = self.registry[client_id]
        except UnknownClient:
            # culled during the (possibly minutes-long) secure phases
            # between cohort sampling and this notify — skip, don't let
            # the exception escape the broadcast gather and 500 the
            # whole cohort's start_round
            return client_id, False
        url = f"{client.url.rstrip('/')}/round_start?client_id={client_id}&key={client.key}"
        # secure broadcasts scale like the share phases (each recipient
        # decrypts O(C) relayed boxes before acking): give them the same
        # cohort-scaled budget instead of aiohttp's default 300 s
        post_kw = ({"timeout": aiohttp.ClientTimeout(
            total=self._secure_phase_budget_s())} if self.secure_agg else {})
        with self.tracer.span("notify", client=client_id), \
                self.metrics.timer("notify_s"):
            return await self._notify_client_traced(
                client_id, url, body, content_type, post_kw
            )

    async def _notify_client_traced(
        self, client_id: str, url: str, body: bytes, content_type: str,
        post_kw: dict,
    ):
        try:
            async with self._session.post(
                url, data=body,
                headers=trace_headers({"Content-Type": content_type}),
                **post_kw,
            ) as resp:
                self.metrics.inc("bytes_broadcast", len(body))
                if resp.status == 200:
                    # record participation NOW, before yielding back to
                    # the loop — this client may upload its update at any
                    # moment after this ack (see start_round)
                    if self.rounds.in_progress:
                        self.rounds.client_start(client_id)
                        return client_id, True
                    return client_id, False
                # observable rejection: a cohort-wide refusal (e.g. every
                # worker 400ing the broadcast) must be distinguishable
                # from "workers never answered" — the C=256 postmortem
                # burned an hour on exactly that ambiguity
                self.metrics.inc(f"broadcast_rejected_{resp.status}")
                # dedup key includes started_at: aborted rounds REUSE
                # their name, so a name-only key would suppress the
                # retry round's first rejection — the exact diagnostic
                # this log exists to surface
                round_key = (self.rounds.round_name, self.rounds.started_at)
                if self._rejection_logged_round != round_key:
                    self._rejection_logged_round = round_key
                    try:
                        body_txt = (await resp.text())[:200]
                    except Exception:
                        body_txt = "<unreadable>"
                    _log.warning(
                        "%s: broadcast rejected by %s: HTTP %d %s "
                        "(first rejection this round; counters track "
                        "the rest)", self.name, client_id, resp.status,
                        body_txt)
                if resp.status == 404:
                    self.registry.drop(client_id)
                    self.rounds.drop_client(client_id)
                return client_id, False
        except asyncio.TimeoutError:
            # alive but slow (e.g. still decrypting its share inbox):
            # skip it this round WITHOUT eviction, and count it — a
            # cohort-wide broadcast timeout must be visible (the C=256
            # silent-abort postmortem)
            self.metrics.inc("broadcast_timeout")
            self.rounds.drop_client(client_id)
            return client_id, False
        except aiohttp.ClientError:
            self.registry.drop(client_id)
            self.rounds.drop_client(client_id)
            return client_id, False

    async def _run_simulated(self, round_name: str, n_epoch: int) -> None:
        """Run the attached FedSim cohort off the event loop and report it
        like any other client."""
        args = self._sim_args

        def on_wave(done: int, total: int) -> None:
            # per-wave heartbeat (engine progress_fn): GET /{name}/metrics
            # shows the cohort's position mid-round, mirroring the
            # worker-side per-epoch hook (http_worker.py)
            self.metrics.set_gauge("sim_wave", done)
            self.metrics.set_gauge("sim_waves_total", total)

        # under a quantized broadcast every participant must start from
        # the identical dequantized weights — including the in-process
        # simulated cohort, which never crosses the wire
        start_params = (
            state_dict_to_params(self.params, self._broadcast_anchor_sd)
            if self._broadcast_anchor_sd is not None
            else self.params
        )

        def run():
            # reset BOTH gauges: a stale total from the previous round
            # would render "0 of <old total>" until the first wave lands
            self.metrics.set_gauge("sim_wave", 0)
            self.metrics.set_gauge("sim_waves_total", 0)
            return self.simulator.run_round(
                start_params,
                args["data"],
                args["n_samples"],
                jax.random.key(self.rounds.n_rounds),
                n_epochs=n_epoch,
                wave_size=args["wave_size"],
                collect_client_losses=False,
                progress_fn=on_wave,
            )

        try:
            result = await asyncio.to_thread(run)
        except Exception as exc:  # XLA/shape/OOM errors must not hang the round
            _log.exception(
                "simulated cohort failed in %s: %s", round_name, exc
            )
            if self.rounds.in_progress and self.rounds.round_name == round_name:
                self.rounds.drop_client("__simulated__")
                self._maybe_finish()
            return
        if not self.rounds.in_progress or self.rounds.round_name != round_name:
            return  # round was force-ended meanwhile
        response = {
            "n_samples": float(result.n_samples_total),
            "loss_history": [float(x) for x in np.asarray(result.loss_history)],
        }
        # the engine leaves its per-round compute record (MFU/compile/
        # HBM) in last_compute; fold it through the same sanitizer the
        # wire path uses so the SLO record sees one schema
        sim_compute = _clean_compute(
            getattr(self.simulator, "last_compute", None)
        )
        if sim_compute is not None:
            response["compute"] = sim_compute
        result_sd = params_to_state_dict(result.params)
        if self._stream_acc is not None:
            # the simulated cohort streams like any other participant;
            # _acc_lock because real uploads fold into the same
            # accumulator from the ingest lanes
            with self._acc_lock:
                self._stream_acc.add(
                    {k: np.asarray(v) for k, v in result_sd.items()},
                    response["n_samples"],
                )
            response["streamed"] = True
        else:
            response["state_dict"] = result_sd
        self.rounds.client_end("__simulated__", response)
        self._maybe_finish()

    def _validate_masked_upload(self, tensors, meta) -> None:
        """A secure-round upload must be EXACTLY the masked uint64 image
        of the full state dict — a missing, extra, mis-typed, or
        mis-shaped tensor would poison the modular sum (or crash
        finalization after the round state is consumed)."""
        if not meta.get("secure"):
            raise ValueError("plain upload in a secure-aggregation round")
        sr = self._secure_round
        if sr is None:
            raise ValueError("no secure round in flight")
        shapes = sr["template_shapes"]
        extra = set(tensors) - set(shapes)
        if extra:
            raise ValueError(f"masked upload has surplus tensors {sorted(extra)}")
        for name, ref_shape in shapes.items():
            arr = tensors.get(name)
            if arr is None:
                raise KeyError(f"masked upload missing tensor {name!r}")
            if np.asarray(arr).dtype != np.uint64:
                raise ValueError(f"masked tensor {name!r} must be uint64")
            if tuple(np.shape(arr)) != ref_shape:
                raise ValueError(f"masked tensor {name!r} has wrong shape")

    def _maybe_finish(self) -> None:
        if self._broadcasting:
            return  # start_round settles the round after the last ack
        if not self.rounds.in_progress:
            return
        if len(self.rounds) == 0:
            # every participant was culled/evicted mid-round: release the
            # round instead of leaving it locked forever (423 on all
            # future start_round calls — the §2.9 item 3 failure class)
            round_name = self.rounds.round_name
            started_wall = self.rounds.started_wall
            self.rounds.abort_round()
            self._secure_round = None
            self._finish_round_obs(
                round_name, "aborted:all_participants_lost",
                started_wall=started_wall,
            )
        elif self.rounds.clients_left == 0:
            self.end_round()
        elif self._fedbuff_buffer_full():
            # fedbuff_fallback actuation: under churn, a buffer's worth
            # of reports is the round — don't wait out the stragglers
            self.end_round()

    def end_round(self) -> None:
        """Aggregate reported weights into the global params — the
        reference FedAvg step (manager.py:113-132) as one XLA call.

        Secure rounds are finalized asynchronously (dropout recovery
        needs HTTP round-trips): this schedules :meth:`_end_round_secure`
        on the running loop, or runs it to completion when called from
        synchronous (test) code."""
        if not self.rounds.in_progress:
            return
        if self._secure_round is not None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                asyncio.run(self._end_round_secure())
            else:
                self._secure_task = loop.create_task(self._end_round_secure())
            return
        n_epoch = (self.rounds.round_meta or {}).get("n_epoch", 0)
        round_name = self.rounds.round_name
        started_wall = self.rounds.started_wall
        participants = set(self.rounds.clients)
        trace_id = tracing.make_trace_id(self.name, round_name)
        self.metrics.observe(
            "round_s", self.rounds.elapsed,
            exemplar=(trace_id, tracing.root_span_id(trace_id)),
        )
        with self._acc_lock:
            acc, self._stream_acc = self._stream_acc, None
        if self._ingest is not None:
            # an accepted update's 200 promised its fold would land in
            # the mean; a forced finish (watchdog expiry, explicit
            # end_round) must wait for folds already on the lanes
            self._ingest.drain_folds()
        responses = self.rounds.end_round()
        self.metrics.inc("rounds_finished")
        try:
            reports = [
                r for r in responses.values() if r.get("n_samples", 0) > 0
            ]
            if not reports:
                return
            with self.tracer.span(
                "aggregate",
                trace_id=trace_id,
                parent_id=tracing.root_span_id(trace_id),
                reports=len(reports),
            ):
                if acc is not None:
                    # streaming FedAvg: the per-update tensors were
                    # folded (and freed) in handle_update — the merge
                    # is one division
                    merged = acc.mean()
                    if merged is None:
                        return
                    self.params = state_dict_to_params(self.params, merged)
                else:
                    weights = jnp.asarray(
                        [r["n_samples"] for r in reports], jnp.float32
                    )
                    template = params_to_state_dict(self.params)
                    stacked = {
                        k: jnp.stack(
                            [np.asarray(r["state_dict"][k]) for r in reports]
                        )
                        for k in template
                    }
                    merged = agg.apply_aggregator(
                        self.aggregator, stacked, weights
                    )
                    self.params = state_dict_to_params(
                        self.params,
                        {k: np.asarray(v) for k, v in merged.items()},
                    )
            self._record_history_and_checkpoint(reports, n_epoch)
        finally:
            self._finish_round_obs(
                round_name, "completed", participants, responses,
                started_wall=started_wall,
            )

    async def _end_round_secure(self) -> None:
        """Secure-round finalization — Bonawitz round 3 (Unmasking).

        The manager can only use the cohort's modular sum. Every
        reporter is asked ONCE for its share bundle under the round's
        survivor/dropped partition; from ≥t shares each, the server
        reconstructs (a) dropped members' mask keys — cancelling their
        uncancelled pairwise masks — and (b) each reporter's self-mask
        seed — removing PRG(b_i). Up to n−t reporters may fail to answer
        and the round still unmasks; below the threshold it aborts and
        the previous global params stand.
        """
        from baton_tpu.server import secure

        sr = self._secure_round
        if (
            sr is None
            or not self.rounds.in_progress
            or self.rounds.round_name != sr["round_name"]
        ):
            return
        if self._secure_finalizing:
            # a finalization is already past this guard and mid-unmask;
            # a second one (watchdog tick / explicit end_round during the
            # await window) must not consume the round out from under it
            return
        self._secure_finalizing = True
        round_name = sr["round_name"]
        started_wall = self.rounds.started_wall
        trace_id = tracing.make_trace_id(self.name, round_name)
        try:
            # a masked upload is a reporter regardless of n_samples: its
            # masks are IN the modular sum, so it must not also be
            # treated as dropped (the correction would double-count);
            # zero-weight reporters contribute exactly 0 to the mean
            reporters = {
                cid: r
                for cid, r in self.rounds.client_responses.items()
                if r.get("masked")
            }
            dropped = sorted(c for c in sr["cohort"] if c not in reporters)
            survivors = sorted(reporters)
            if not reporters or len(survivors) < sr["t"]:
                # below the Shamir threshold nothing can be unmasked
                self.metrics.inc("secure_rounds_unrecoverable")
                self.rounds.abort_round()
                self._secure_round = None
                self._finish_round_obs(
                    round_name, "aborted:secure_below_threshold",
                    sr["cohort"], reporters, started_wall=started_wall,
                )
                return
            template = params_to_state_dict(self.params)
            with self.tracer.span(
                "secure_unmask",
                trace_id=trace_id,
                parent_id=tracing.root_span_id(trace_id),
                survivors=len(survivors),
                dropped=len(dropped),
            ):
                bundles = await bounded_gather(
                    *[
                        self._request_unmask(
                            rid, sr["round_name"], survivors, dropped,
                            sr["c_pks"][rid],
                        )
                        for rid in survivors
                    ],
                    limit=self.fanout_concurrency,
                )
            # collect shares by secret owner; x-indices were fixed at
            # share time, so partial responses compose correctly
            b_shares: Dict[str, Dict[int, int]] = {s: {} for s in survivors}
            csk_shares: Dict[str, Dict[int, int]] = {d: {} for d in dropped}
            for rid, bundle in zip(survivors, bundles):
                if bundle is None:
                    continue
                try:
                    x = int(bundle["x"])
                    if x != sr["index"].get(rid):
                        continue  # mislabeled shares would corrupt Lagrange
                    for cid, h in dict(bundle.get("b_shares", {})).items():
                        if cid in b_shares:
                            b_shares[cid][x] = secure.share_from_hex(str(h))
                    for cid, h in dict(bundle.get("csk_shares", {})).items():
                        if cid in csk_shares:
                            csk_shares[cid][x] = secure.share_from_hex(str(h))
                except (KeyError, ValueError, TypeError):
                    continue
            if self._secure_round is not sr or not self.rounds.in_progress:
                # round replaced/aborted during the unmask HTTP
                # round-trips — same ownership rule as after the
                # reconstruction thread below (identity, not name:
                # aborted rounds reuse their name)
                return
            t = sr["t"]
            short = [
                cid
                for cid, shs in list(b_shares.items()) + list(csk_shares.items())
                if len(shs) < t
            ]
            if short:
                # too many unmask responders failed: below threshold for
                # at least one secret — the sum cannot be opened
                self.metrics.inc("secure_rounds_unrecoverable")
                self.rounds.abort_round()
                self._secure_round = None
                self._finish_round_obs(
                    round_name, "aborted:secure_shares_short",
                    sr["cohort"], reporters, started_wall=started_wall,
                )
                return
            # Reconstruction + mask regeneration + the modular sum are
            # the round's heaviest host compute — O(dropped×survivors)
            # 2048-bit modexps plus O(C) Philox masks over the full
            # template (measured: ~20 s at 128 members with 32 dropped).
            # Run it all in a worker thread: on the loop it starved
            # heartbeats/uploads for every co-located client (the r4
            # secure_round_scale run recorded 26 starvation dropouts).
            # Aggregate the SNAPSHOTTED reporter set, not whatever lands
            # in the round state meanwhile: a straggler in `dropped`
            # that uploaded during an await window would otherwise be
            # counted in the sum while its masks are also 'corrected' —
            # leaving uncancelled mask noise in the params.
            # (handle_update additionally 410s those stragglers; this
            # is the backstop.)
            reports = list(reporters.values())

            def _reconstruct_and_open():
                corrections = []
                for d in dropped:
                    c_sk = secure.shamir_reconstruct(
                        dict(list(csk_shares[d].items())[:t])
                    )
                    seeds = {
                        rid: secure.dh_shared_seed(
                            c_sk, sr["c_pks"][rid], sr["round_name"]
                        )
                        for rid in survivors
                    }
                    corrections.append(
                        secure.dropout_correction(d, seeds, template)
                    )
                    # the reconstructed key exists only to cancel this
                    # round's residues — purge its cached DH powers so
                    # the dropped client's pairwise secrets don't
                    # outlive the finalization (secure.py
                    # forward-secrecy contract)
                    secure.purge_dh_secrets(c_sk)
                self_seeds = []
                for s_cid in survivors:
                    b_int = secure.shamir_reconstruct(
                        dict(list(b_shares[s_cid].items())[:t])
                    )
                    if b_int >> 256:
                        # a corrupt share makes the interpolation land
                        # almost surely outside the 256-bit seed range —
                        # the sum cannot be opened correctly
                        return None
                    self_seeds.append(b_int.to_bytes(32, "big"))
                corrections.append(
                    secure.self_mask_correction(self_seeds, template)
                )
                masked_sum = secure.modular_sum(
                    [r["state_dict"] for r in reports]
                )
                return secure.unmask_sum(
                    masked_sum, corrections, sr["scale_bits"]
                )

            total = await asyncio.to_thread(_reconstruct_and_open)
            if self._secure_round is not sr or not self.rounds.in_progress:
                # the round was aborted (or a NEW round started) while
                # the reconstruction thread ran — in either case this
                # finalization owns nothing anymore and must not touch
                # the current round's state. Identity (`is sr`), not the
                # round name: aborted rounds REUSE their name (reference
                # naming parity, rounds.py::abort_round), so a replacement
                # round is indistinguishable by name alone.
                return
            if total is None:
                # abort, don't crash the finalize task (which would
                # lock the round forever)
                self.metrics.inc("secure_rounds_unrecoverable")
                self.rounds.abort_round()
                self._secure_round = None
                self._finish_round_obs(
                    round_name, "aborted:secure_unmask_failed",
                    sr["cohort"], reporters, started_wall=started_wall,
                )
                return
            if dropped:
                self.metrics.inc("secure_dropouts_recovered", len(dropped))
            n_epoch = (self.rounds.round_meta or {}).get("n_epoch", 0)
            _rt = tracing.make_trace_id(self.name, round_name)
            self.metrics.observe(
                "round_s", self.rounds.elapsed,
                exemplar=(_rt, tracing.root_span_id(_rt)),
            )
            self.rounds.end_round()
            self.metrics.inc("rounds_finished")
            w = sum(float(r["n_samples"]) for r in reports)
            if w > 0:
                merged = {k: v / w for k, v in total.items()}
                self.params = state_dict_to_params(self.params, merged)
                self._record_history_and_checkpoint(reports, n_epoch)
            self._secure_round = None
            self._finish_round_obs(
                round_name, "completed_secure",
                sr["cohort"], reporters, started_wall=started_wall,
            )
        finally:
            self._secure_finalizing = False

    def _record_history_and_checkpoint(self, reports, n_epoch) -> None:
        # loss history: sample-weighted per-epoch mean (manager.py:127-130)
        appended = []
        for epoch in range(n_epoch):
            num = sum(
                r["loss_history"][epoch] * r["n_samples"]
                for r in reports
                if len(r["loss_history"]) > epoch
            )
            den = sum(
                r["n_samples"] for r in reports if len(r["loss_history"]) > epoch
            )
            if den:
                self.rounds.loss_history.append(num / den)
                appended.append(float(num / den))
        if self.journal is not None:
            if appended:
                self.journal.append("losses_appended", values=appended)
            self._compact_journal()
        if self.checkpointer is not None:
            # Even with wait=False, orbax's save() blocks synchronously on
            # any still-in-flight previous async save — under slow storage
            # back-to-back rounds would stall the event loop (heartbeats
            # pause, live clients get culled). Run the whole save call in
            # a worker thread so the loop never waits on storage; orbax
            # serializes concurrent saves internally and writes atomically
            # (temp dir + rename); close() drains in-flight saves.
            import asyncio

            step = self.rounds.n_rounds
            meta = {
                "n_rounds": step,
                "loss_history": [float(x) for x in self.rounds.loss_history],
            }

            async def _save(params=self.params):
                with self.metrics.timer("checkpoint_s"):
                    await asyncio.to_thread(
                        self.checkpointer.save, step, params,
                        meta=meta, wait=False,
                    )

            try:
                asyncio.get_running_loop()
                self._checkpoint_task = asyncio.ensure_future(_save())
            except RuntimeError:
                # end_round called outside the event loop (direct unit
                # tests): save inline, there is no loop to stall
                with self.metrics.timer("checkpoint_s"):
                    self.checkpointer.save(
                        step, self.params, meta=meta, wait=False
                    )

    def _compact_journal(self) -> None:
        """Snapshot the full control-plane state and truncate the journal.
        Runs at round end (a quiescent point — the snapshot schema has no
        open round) so the journal only ever holds one round's events."""
        if self.rounds.in_progress:
            return
        from baton_tpu.server.journal import registry_snapshot

        self.journal.compact(
            {
                "clients": registry_snapshot(self.registry),
                "n_rounds": self.rounds.n_rounds,
                "loss_history": [
                    float(x) for x in self.rounds.loss_history
                ],
                # leadership must survive compaction: a standby that
                # catches up from this snapshot (or a restart that
                # replays it) must not mint an epoch below the fence
                "ha_epoch": self.ha_epoch,
            }
        )

    def round_state(self) -> dict:
        return {
            "name": self.name,
            "round": self.rounds.round_name,
            "n_rounds": self.rounds.n_rounds,
            "in_progress": self.rounds.in_progress,
            "clients": sorted(self.rounds.clients),
            "reported": sorted(self.rounds.client_responses),
            "loss_history": [float(x) for x in self.rounds.loss_history],
        }
